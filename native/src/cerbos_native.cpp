// cerbos_native: CPython extension for host-side hot paths.
//
// Native runtime pieces around the JAX/XLA compute path (the reference has no
// native code to mirror — SURVEY.md notes the obligation attaches to the new
// evaluator; these are the host analogues of internal/util/globs_common.go and
// the index's per-dimension matchers):
//
//   glob_match(pattern, value)        gobwas-style glob with ':' separator
//   glob_match_many(patterns, value)  indices of matching patterns
//   encode_double_keys(float64 buf)   order-preserving (hi, lo) int32 pairs
//
// Built with plain g++ (no pybind11 in the image); loaded by
// cerbos_tpu/native.py with a pure-Python fallback.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

#if defined(__linux__)
#include <linux/futex.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>
#endif

namespace {

constexpr char kSeparator = ':';

// Recursive glob matcher. Pattern syntax (gobwas/glob with separators={':'}):
//   *      any run of non-separator chars
//   **     any run of any chars
//   ?      one non-separator char
//   [ab] / [!ab] / [a-z]   char class (single char, separator-agnostic)
//   {a,b}  alternates (may contain nested patterns)
//   \x     literal x
// A bare "*" pattern is promoted to "**" by the caller (fixGlob behavior).
bool MatchGlob(const char* p, size_t plen, const char* v, size_t vlen, int depth);

// Matches a brace alternate set starting at p (just past '{'). Returns the
// offset of the char after the closing '}' via out_end, and fills alts with
// (start, len) pairs of each alternative.
bool SplitAlternates(const char* p, size_t plen, size_t* out_end,
                     std::vector<std::pair<size_t, size_t>>* alts) {
  size_t depth = 1;
  bool in_class = false;  // commas inside [...] are not separators
  size_t start = 0;
  for (size_t i = 0; i < plen; i++) {
    char c = p[i];
    if (c == '\\' && i + 1 < plen) {
      i++;
      continue;
    }
    if (in_class) {
      if (c == ']') in_class = false;
      continue;
    }
    if (c == '[') {
      in_class = true;
    } else if (c == '{') {
      depth++;
    } else if (c == '}') {
      depth--;
      if (depth == 0) {
        alts->emplace_back(start, i - start);
        *out_end = i + 1;
        return true;
      }
    } else if (c == ',' && depth == 1) {
      alts->emplace_back(start, i - start);
      start = i + 1;
    }
  }
  return false;  // unterminated
}

bool MatchClass(const char* body, size_t blen, bool negate, char c) {
  bool hit = false;
  for (size_t i = 0; i < blen; i++) {
    if (i + 2 < blen && body[i + 1] == '-') {
      if (c >= body[i] && c <= body[i + 2]) hit = true;
      i += 2;
    } else if (body[i] == c) {
      hit = true;
    }
  }
  return negate ? !hit : hit;
}

bool MatchGlob(const char* p, size_t plen, const char* v, size_t vlen, int depth) {
  if (depth > 64) return false;  // pathological nesting guard
  size_t pi = 0, vi = 0;
  while (pi < plen) {
    char pc = p[pi];
    if (pc == '*') {
      bool super = (pi + 1 < plen && p[pi + 1] == '*');
      size_t rest = pi + (super ? 2 : 1);
      // try all split points (greedy backtracking)
      for (size_t skip = 0; vi + skip <= vlen; skip++) {
        if (!super && skip > 0 && v[vi + skip - 1] == kSeparator) break;
        if (MatchGlob(p + rest, plen - rest, v + vi + skip, vlen - vi - skip, depth + 1)) {
          return true;
        }
      }
      return false;
    }
    if (pc == '{') {
      std::vector<std::pair<size_t, size_t>> alts;
      size_t end = 0;
      if (!SplitAlternates(p + pi + 1, plen - pi - 1, &end, &alts)) {
        // unterminated: literal '{'
        if (vi >= vlen || v[vi] != '{') return false;
        pi++;
        vi++;
        continue;
      }
      size_t after = pi + 1 + end;
      for (const auto& alt : alts) {
        // splice alternative + rest of pattern
        std::string combined(p + pi + 1 + alt.first, alt.second);
        combined.append(p + after, plen - after);
        if (MatchGlob(combined.data(), combined.size(), v + vi, vlen - vi, depth + 1)) {
          return true;
        }
      }
      return false;
    }
    if (vi >= vlen) return false;
    if (pc == '?') {
      if (v[vi] == kSeparator) return false;
      pi++;
      vi++;
      continue;
    }
    if (pc == '[') {
      size_t j = pi + 1;
      bool negate = (j < plen && p[j] == '!');
      if (negate) j++;
      size_t k = j;
      if (k < plen && p[k] == ']') k++;  // literal ']' first member
      while (k < plen && p[k] != ']') k++;
      if (k >= plen) {  // unterminated: literal '['
        if (v[vi] != '[') return false;
        pi++;
        vi++;
        continue;
      }
      if (!MatchClass(p + j, k - j, negate, v[vi])) return false;
      pi = k + 1;
      vi++;
      continue;
    }
    if (pc == '\\' && pi + 1 < plen) {
      if (v[vi] != p[pi + 1]) return false;
      pi += 2;
      vi++;
      continue;
    }
    if (v[vi] != pc) return false;
    pi++;
    vi++;
  }
  return vi == vlen;
}

bool MatchTop(const char* p, Py_ssize_t plen, const char* v, Py_ssize_t vlen) {
  // fixGlob: bare "*" means "**"
  if (plen == 1 && p[0] == '*') return true;
  return MatchGlob(p, static_cast<size_t>(plen), v, static_cast<size_t>(vlen), 0);
}

PyObject* PyGlobMatch(PyObject*, PyObject* args) {
  const char* pattern;
  Py_ssize_t plen;
  const char* value;
  Py_ssize_t vlen;
  if (!PyArg_ParseTuple(args, "s#s#", &pattern, &plen, &value, &vlen)) return nullptr;
  if (MatchTop(pattern, plen, value, vlen)) Py_RETURN_TRUE;
  Py_RETURN_FALSE;
}

PyObject* PyGlobMatchMany(PyObject*, PyObject* args) {
  PyObject* patterns;
  const char* value;
  Py_ssize_t vlen;
  if (!PyArg_ParseTuple(args, "Os#", &patterns, &value, &vlen)) return nullptr;
  PyObject* seq = PySequence_Fast(patterns, "patterns must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  PyObject* out = PyList_New(0);
  if (out == nullptr) {
    Py_DECREF(seq);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    Py_ssize_t plen;
    const char* pattern = PyUnicode_AsUTF8AndSize(item, &plen);
    if (pattern == nullptr) {
      Py_DECREF(seq);
      Py_DECREF(out);
      return nullptr;
    }
    if (MatchTop(pattern, plen, value, vlen)) {
      PyObject* idx = PyLong_FromSsize_t(i);
      PyList_Append(out, idx);
      Py_DECREF(idx);
    }
  }
  Py_DECREF(seq);
  return out;
}

// encode_double_keys(input_buffer_f64) -> (bytes_hi_i32, bytes_lo_i32, bytes_nan_u8)
PyObject* PyEncodeDoubleKeys(PyObject*, PyObject* args) {
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "y*", &buf)) return nullptr;
  if (buf.len % 8 != 0) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "buffer length must be a multiple of 8");
    return nullptr;
  }
  Py_ssize_t n = buf.len / 8;
  PyObject* hi_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject* lo_b = PyBytes_FromStringAndSize(nullptr, n * 4);
  PyObject* nan_b = PyBytes_FromStringAndSize(nullptr, n);
  if (!hi_b || !lo_b || !nan_b) {
    Py_XDECREF(hi_b);
    Py_XDECREF(lo_b);
    Py_XDECREF(nan_b);
    PyBuffer_Release(&buf);
    return nullptr;
  }
  const uint64_t* in = static_cast<const uint64_t*>(buf.buf);
  int32_t* hi = reinterpret_cast<int32_t*>(PyBytes_AS_STRING(hi_b));
  int32_t* lo = reinterpret_cast<int32_t*>(PyBytes_AS_STRING(lo_b));
  uint8_t* nan = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(nan_b));
  for (Py_ssize_t i = 0; i < n; i++) {
    uint64_t bits = in[i];
    double d;
    std::memcpy(&d, &bits, 8);
    bool is_nan = d != d;
    if (d == 0.0) {  // -0.0 == 0.0 in CEL: same key
      d = 0.0;
      std::memcpy(&bits, &d, 8);
    }
    uint64_t key;
    if (bits & (1ULL << 63)) {
      key = ~bits;
    } else {
      key = bits | (1ULL << 63);
    }
    // sign-bias each word so signed int32 comparison preserves key order
    uint32_t h = static_cast<uint32_t>(key >> 32) ^ 0x80000000u;
    uint32_t l = static_cast<uint32_t>(key & 0xFFFFFFFFULL) ^ 0x80000000u;
    hi[i] = static_cast<int32_t>(h);
    lo[i] = static_cast<int32_t>(l);
    nan[i] = is_nan ? 1 : 0;
  }
  PyBuffer_Release(&buf);
  PyObject* result = PyTuple_Pack(3, hi_b, lo_b, nan_b);
  Py_DECREF(hi_b);
  Py_DECREF(lo_b);
  Py_DECREF(nan_b);
  return result;
}

// One value → (tag, hi, lo, sid, nan) at slot i. Returns -1 on allocation
// failure (Python error set), 0 otherwise. TAG codes (columns.py):
// MISSING=0 NULL=1 BOOL=2 NUM=3 STR=4 OTHER=5 ERR=6.
int EncodeOne(PyObject* v, PyObject* interner, PyObject* missing,
              PyObject* err, Py_ssize_t i, uint8_t* tags, int32_t* hi,
              int32_t* lo, int32_t* sid, uint8_t* nan) {
  tags[i] = 0;
  hi[i] = 0;
  lo[i] = 0;
  sid[i] = 0;
  nan[i] = 0;
  if (v == missing) {
    return 0;  // TAG_MISSING zeros
  }
  if (v == err) {
    tags[i] = 6;
    return 0;
  }
  if (v == Py_None) {
    tags[i] = 1;
    return 0;
  }
  if (PyBool_Check(v)) {
    tags[i] = 2;
    hi[i] = (v == Py_True) ? 1 : 0;
    return 0;
  }
  double d;
  // subtype-tolerant (np.float64, IntEnum...) to match encode_value's
  // isinstance checks; bool was already handled above
  if (PyFloat_Check(v)) {
    d = PyFloat_AS_DOUBLE(v);
  } else if (PyLong_Check(v)) {
    d = PyLong_AsDouble(v);
    if (d == -1.0 && PyErr_Occurred()) {
      PyErr_Clear();
      tags[i] = 5;  // magnitude beyond double: host/oracle territory
      return 0;
    }
  } else if (PyUnicode_Check(v)) {
    tags[i] = 4;
    PyObject* id_obj = PyDict_GetItem(interner, v);  // borrowed
    long id;
    if (id_obj != nullptr) {
      id = PyLong_AsLong(id_obj);
    } else {
      id = static_cast<long>(PyDict_Size(interner)) + 1;
      PyObject* new_id = PyLong_FromLong(id);
      if (!new_id || PyDict_SetItem(interner, v, new_id) < 0) {
        Py_XDECREF(new_id);
        return -1;
      }
      Py_DECREF(new_id);
    }
    sid[i] = static_cast<int32_t>(id);
    return 0;
  } else {
    tags[i] = 5;  // lists/dicts/other
    return 0;
  }
  // numeric path (float or in-range int)
  tags[i] = 3;
  if (d != d) {
    nan[i] = 1;
    return 0;
  }
  if (d == 0.0) d = 0.0;  // collapse -0.0
  uint64_t bits;
  std::memcpy(&bits, &d, 8);
  uint64_t key = (bits & (1ULL << 63)) ? ~bits : (bits | (1ULL << 63));
  hi[i] = static_cast<int32_t>(static_cast<uint32_t>(key >> 32) ^ 0x80000000u);
  lo[i] = static_cast<int32_t>(static_cast<uint32_t>(key) ^ 0x80000000u);
  return 0;
}

// encode_column(values, interner_dict, missing, err,
//               tags_u8, hi_i32, lo_i32, sid_i32, nan_u8) -> None
//
// One column's batch encoding (columns.py encode_value semantics over a
// whole [B] list): per element writes (tag, hi, lo, sid, nan) into the
// writable buffers. String ids come from / are added to interner_dict
// (str -> int, ids start at 1 — StringInterner). `missing` / `err` are the
// packer's sentinel objects compared by identity.
PyObject* PyEncodeColumn(PyObject*, PyObject* args) {
  PyObject* values;
  PyObject* interner;
  PyObject* missing;
  PyObject* err;
  Py_buffer tags_b, hi_b, lo_b, sid_b, nan_b;
  if (!PyArg_ParseTuple(args, "OO!OOw*w*w*w*w*", &values, &PyDict_Type,
                        &interner, &missing, &err, &tags_b, &hi_b, &lo_b,
                        &sid_b, &nan_b)) {
    return nullptr;
  }
  struct Bufs {
    Py_buffer *a, *b, *c, *d, *e;
    ~Bufs() {
      PyBuffer_Release(a);
      PyBuffer_Release(b);
      PyBuffer_Release(c);
      PyBuffer_Release(d);
      PyBuffer_Release(e);
    }
  } release{&tags_b, &hi_b, &lo_b, &sid_b, &nan_b};

  PyObject* seq = PySequence_Fast(values, "values must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (tags_b.len < n || nan_b.len < n ||
      hi_b.len < static_cast<Py_ssize_t>(n * 4) ||
      lo_b.len < static_cast<Py_ssize_t>(n * 4) ||
      sid_b.len < static_cast<Py_ssize_t>(n * 4)) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "output buffers too small");
    return nullptr;
  }
  uint8_t* tags = static_cast<uint8_t*>(tags_b.buf);
  int32_t* hi = static_cast<int32_t*>(hi_b.buf);
  int32_t* lo = static_cast<int32_t*>(lo_b.buf);
  int32_t* sid = static_cast<int32_t*>(sid_b.buf);
  uint8_t* nan = static_cast<uint8_t*>(nan_b.buf);

  // TAG codes (columns.py): MISSING=0 NULL=1 BOOL=2 NUM=3 STR=4 OTHER=5 ERR=6
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* v = PySequence_Fast_GET_ITEM(seq, i);
    if (EncodeOne(v, interner, missing, err, i, tags, hi, lo, sid, nan) < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

// Shared value gather for the packer's fused column modes (see
// PyEncodeAttrColumn). Returns a NEW reference (or `missing` borrowed with
// an extra ref) — caller decrefs.
PyObject* GatherValue(PyObject* inp, int mode, PyObject* root, PyObject* leaf,
                      PyObject* missing, PyObject* attr_name,
                      PyObject* aux_name, PyObject* jwt_name) {
  if (mode == 0) {
    PyObject* obj = PyObject_GetAttr(inp, root);
    if (!obj) {
      PyErr_Clear();
    } else {
      PyObject* attrs = PyObject_GetAttr(obj, attr_name);
      Py_DECREF(obj);
      if (!attrs) {
        PyErr_Clear();
      } else {
        if (PyDict_Check(attrs)) {
          PyObject* got = PyDict_GetItemWithError(attrs, leaf);  // borrowed
          if (got) {
            Py_INCREF(got);
            Py_DECREF(attrs);
            return got;
          }
          if (PyErr_Occurred()) PyErr_Clear();
        }
        Py_DECREF(attrs);
      }
    }
  } else if (mode == 1) {
    PyObject* aux = PyObject_GetAttr(inp, aux_name);
    if (!aux) {
      PyErr_Clear();
    } else {
      if (aux != Py_None) {
        PyObject* jwt = PyObject_GetAttr(aux, jwt_name);
        if (!jwt) {
          PyErr_Clear();
        } else {
          if (PyDict_Check(jwt)) {
            PyObject* got = PyDict_GetItemWithError(jwt, leaf);  // borrowed
            if (got) {
              Py_INCREF(got);
              Py_DECREF(jwt);
              Py_DECREF(aux);
              return got;
            }
            if (PyErr_Occurred()) PyErr_Clear();
          }
          Py_DECREF(jwt);
        }
      }
      Py_DECREF(aux);
    }
  } else {
    PyObject* obj = PyObject_GetAttr(inp, root);
    if (obj) {
      PyObject* got = PyObject_GetAttr(obj, leaf);
      Py_DECREF(obj);
      if (got) return got;
      PyErr_Clear();
    } else {
      PyErr_Clear();
    }
  }
  Py_INCREF(missing);
  return missing;
}

// encode_attr_column(inputs, mode, root, leaf, interner, missing, err,
//                    tags_u8, hi_i32, lo_i32, sid_i32, nan_u8
//                    [, subtype_u8]) -> None
//
// Fused gather + encode for the packer's common column shapes: the value
// resolution (Python attribute access per input) AND the type dispatch /
// key encoding run in one C loop, so no per-input Python frames and no
// intermediate values list. Modes mirror packer._path_accessor:
//   0: getattr(inp, root).attr.get(leaf)        — attr leaves
//   1: inp.aux_data → .jwt.get(leaf)            — JWT claims
//   2: getattr(getattr(inp, root), leaf)        — top-level fields
//
// The optional subtype buffer records information the (tag, hi, lo) key
// erases but CEL semantics keep: 0 = n/a, 1 = float, 2 = int exactly
// representable as double, 3 = int NOT exactly representable (key is
// lossy). Callers that group values by key need it to avoid collapsing
// CEL-distinct numerics (int 1 vs double 1.0, 2^53 vs 2^53+1).
PyObject* PyEncodeAttrColumn(PyObject*, PyObject* args) {
  PyObject* inputs;
  int mode;
  PyObject* root;
  PyObject* leaf;
  PyObject* interner;
  PyObject* missing;
  PyObject* err;
  Py_buffer tags_b, hi_b, lo_b, sid_b, nan_b;
  Py_buffer subtype_b;
  subtype_b.buf = nullptr;
  if (!PyArg_ParseTuple(args, "OiUUO!OOw*w*w*w*w*|w*", &inputs, &mode, &root,
                        &leaf, &PyDict_Type, &interner, &missing, &err,
                        &tags_b, &hi_b, &lo_b, &sid_b, &nan_b, &subtype_b)) {
    return nullptr;
  }
  struct Bufs {
    Py_buffer *a, *b, *c, *d, *e, *f;
    ~Bufs() {
      PyBuffer_Release(a);
      PyBuffer_Release(b);
      PyBuffer_Release(c);
      PyBuffer_Release(d);
      PyBuffer_Release(e);
      if (f->buf) PyBuffer_Release(f);
    }
  } release{&tags_b, &hi_b, &lo_b, &sid_b, &nan_b, &subtype_b};

  PyObject* seq = PySequence_Fast(inputs, "inputs must be a sequence");
  if (!seq) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (tags_b.len < n || nan_b.len < n ||
      hi_b.len < static_cast<Py_ssize_t>(n * 4) ||
      lo_b.len < static_cast<Py_ssize_t>(n * 4) ||
      sid_b.len < static_cast<Py_ssize_t>(n * 4)) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "output buffers too small");
    return nullptr;
  }
  uint8_t* tags = static_cast<uint8_t*>(tags_b.buf);
  int32_t* hi = static_cast<int32_t*>(hi_b.buf);
  int32_t* lo = static_cast<int32_t*>(lo_b.buf);
  int32_t* sid = static_cast<int32_t*>(sid_b.buf);
  uint8_t* nan = static_cast<uint8_t*>(nan_b.buf);
  uint8_t* subtype = static_cast<uint8_t*>(subtype_b.buf);  // may be null
  if (subtype && subtype_b.len < n) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "subtype buffer too small");
    return nullptr;
  }

  static PyObject* attr_name = nullptr;  // interned "attr"
  static PyObject* aux_name = nullptr;   // interned "aux_data"
  static PyObject* jwt_name = nullptr;   // interned "jwt"
  if (!attr_name) attr_name = PyUnicode_InternFromString("attr");
  if (!aux_name) aux_name = PyUnicode_InternFromString("aux_data");
  if (!jwt_name) jwt_name = PyUnicode_InternFromString("jwt");

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* inp = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* v = GatherValue(inp, mode, root, leaf, missing, attr_name,
                              aux_name, jwt_name);  // owned
    int rc = EncodeOne(v, interner, missing, err, i, tags, hi, lo, sid, nan);
    if (subtype) {
      uint8_t st = 0;
      if (v != missing && v != err && !PyBool_Check(v)) {
        if (PyFloat_Check(v)) {
          st = 1;
        } else if (PyLong_Check(v)) {
          double d = PyLong_AsDouble(v);
          if (d == -1.0 && PyErr_Occurred()) {
            PyErr_Clear();
            st = 3;  // beyond double: key is lossy
          } else {
            PyObject* fl = PyFloat_FromDouble(d);
            if (fl) {
              // Python int==float comparison is exact (arbitrary precision)
              int eq = PyObject_RichCompareBool(v, fl, Py_EQ);
              Py_DECREF(fl);
              if (eq < 0) PyErr_Clear();
              st = (eq == 1) ? 2 : 3;
            } else {
              PyErr_Clear();
              st = 3;
            }
          }
        }
      }
      subtype[i] = st;
    }
    Py_DECREF(v);
    if (rc < 0) {
      Py_DECREF(seq);
      return nullptr;
    }
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

// encode_list_column(inputs, mode, root, leaf, interner, missing,
//                    state_u8_buf) -> (width, sids_bytes)
//
// Fused gather + intern for string-list membership columns
// (packer._encode_list_columns semantics): per input
//   missing attr        -> state 0
//   dict value          -> state 3 (caller routes the plan to the oracle)
//   non-list            -> state 2 (CEL error on device)
//   list                -> state 1; str elements interned, non-str -> sid 0
// The sid matrix is zero-padded to width = pow2(max_len, >=4) so jit traces
// reuse across batches; returned as raw little-endian int32 bytes [n, width].
PyObject* PyEncodeListColumn(PyObject*, PyObject* args) {
  PyObject* inputs;
  int mode;
  PyObject* root;
  PyObject* leaf;
  PyObject* interner;
  PyObject* missing;
  Py_buffer state_b;
  if (!PyArg_ParseTuple(args, "OiUUO!Ow*", &inputs, &mode, &root, &leaf,
                        &PyDict_Type, &interner, &missing, &state_b)) {
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(inputs, "inputs must be a sequence");
  if (!seq) {
    PyBuffer_Release(&state_b);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (state_b.len < n) {
    Py_DECREF(seq);
    PyBuffer_Release(&state_b);
    PyErr_SetString(PyExc_ValueError, "state buffer too small");
    return nullptr;
  }
  uint8_t* state = static_cast<uint8_t*>(state_b.buf);

  static PyObject* attr_name = nullptr;
  static PyObject* aux_name = nullptr;
  static PyObject* jwt_name = nullptr;
  if (!attr_name) attr_name = PyUnicode_InternFromString("attr");
  if (!aux_name) aux_name = PyUnicode_InternFromString("aux_data");
  if (!jwt_name) jwt_name = PyUnicode_InternFromString("jwt");

  std::vector<PyObject*> vals(static_cast<size_t>(n));
  Py_ssize_t max_len = 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* inp = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* v = GatherValue(inp, mode, root, leaf, missing, attr_name,
                              aux_name, jwt_name);
    vals[static_cast<size_t>(i)] = v;
    if (PyList_Check(v)) {
      Py_ssize_t len = PyList_GET_SIZE(v);
      if (len > max_len) max_len = len;
    }
  }
  Py_ssize_t width = 4;
  while (width < max_len) width *= 2;

  PyObject* sids_b = PyBytes_FromStringAndSize(nullptr, n * width * 4);
  if (!sids_b) {
    for (PyObject* v : vals) Py_DECREF(v);
    Py_DECREF(seq);
    PyBuffer_Release(&state_b);
    return nullptr;
  }
  int32_t* sids = reinterpret_cast<int32_t*>(PyBytes_AS_STRING(sids_b));
  std::memset(sids, 0, static_cast<size_t>(n * width * 4));

  bool fail = false;
  for (Py_ssize_t i = 0; i < n && !fail; i++) {
    PyObject* v = vals[static_cast<size_t>(i)];
    if (v == missing) {
      state[i] = 0;
    } else if (PyDict_Check(v)) {
      state[i] = 3;  // map membership is key membership: oracle territory
    } else if (!PyList_Check(v)) {
      state[i] = 2;
    } else {
      state[i] = 1;
      Py_ssize_t len = PyList_GET_SIZE(v);
      int32_t* row = sids + i * width;
      for (Py_ssize_t j = 0; j < len; j++) {
        PyObject* el = PyList_GET_ITEM(v, j);
        if (!PyUnicode_Check(el)) {
          row[j] = 0;  // non-string never equals a string constant
          continue;
        }
        PyObject* id_obj = PyDict_GetItem(interner, el);  // borrowed
        long id;
        if (id_obj != nullptr) {
          id = PyLong_AsLong(id_obj);
        } else {
          id = static_cast<long>(PyDict_Size(interner)) + 1;
          PyObject* new_id = PyLong_FromLong(id);
          if (!new_id || PyDict_SetItem(interner, el, new_id) < 0) {
            Py_XDECREF(new_id);
            fail = true;
            break;
          }
          Py_DECREF(new_id);
        }
        row[j] = static_cast<int32_t>(id);
      }
    }
  }
  for (PyObject* v : vals) Py_DECREF(v);
  Py_DECREF(seq);
  PyBuffer_Release(&state_b);
  if (fail) {
    Py_DECREF(sids_b);
    return nullptr;
  }
  PyObject* width_obj = PyLong_FromSsize_t(width);
  PyObject* result = PyTuple_Pack(2, width_obj, sids_b);
  Py_DECREF(width_obj);
  Py_DECREF(sids_b);
  return result;
}

// resolve_effects(BA, K, J, D, C, ba_input_i32, cand_cond_i32, cand_drcond_i32,
//                 cand_effect_i8, cand_pt_i8, cand_depth_i8, cand_valid_u8,
//                 scope_sp_i8, sat_cond_u8, allow_code, deny_code, sp_override,
//                 final_i8[BA*4], role_results_i8[BA*K*2*2], win_j_i8[BA*K*2])
//
// The effect-resolution lattice (evaluator._compute's post-sat half) as one
// fused pass: per (input,action) cell walk roles × depths, first-DENY /
// first-ALLOW-with-OVERRIDE per depth, then the role/policy-type merge.
// Semantically identical to the numpy/jax lattice — the numpy fallback calls
// this to replace ~40 small-array kernel launches with one memory pass; the
// jax path keeps the XLA lattice for device execution.
PyObject* PyResolveEffects(PyObject*, PyObject* args) {
  int BA, K, J, D, C;
  Py_buffer ba_b, cc_b, cd_b, ce_b, cp_b, cdep_b, cv_b, sp_b, sat_b;
  int allow_code, deny_code, sp_override;
  Py_buffer fin_b, rr_b, wj_b;
  if (!PyArg_ParseTuple(args, "iiiiiy*y*y*y*y*y*y*y*y*iiiw*w*w*", &BA, &K, &J,
                        &D, &C, &ba_b, &cc_b, &cd_b, &ce_b, &cp_b, &cdep_b,
                        &cv_b, &sp_b, &sat_b, &allow_code, &deny_code,
                        &sp_override, &fin_b, &rr_b, &wj_b)) {
    return nullptr;
  }
  struct Bufs {
    std::vector<Py_buffer*> bufs;
    ~Bufs() {
      for (auto* b : bufs) PyBuffer_Release(b);
    }
  } release{{&ba_b, &cc_b, &cd_b, &ce_b, &cp_b, &cdep_b, &cv_b, &sp_b, &sat_b,
             &fin_b, &rr_b, &wj_b}};
  const Py_ssize_t cells = static_cast<Py_ssize_t>(BA) * K * J;
  if (ba_b.len < static_cast<Py_ssize_t>(BA * 4) ||
      cc_b.len < cells * 4 || cd_b.len < cells * 4 || ce_b.len < cells ||
      cp_b.len < cells || cdep_b.len < cells || cv_b.len < cells ||
      fin_b.len < static_cast<Py_ssize_t>(BA) * 4 ||
      rr_b.len < static_cast<Py_ssize_t>(BA) * K * 4 ||
      wj_b.len < static_cast<Py_ssize_t>(BA) * K * 2) {
    PyErr_SetString(PyExc_ValueError, "buffer sizes inconsistent");
    return nullptr;
  }
  const int32_t* ba_input = static_cast<const int32_t*>(ba_b.buf);
  const int32_t* cand_cond = static_cast<const int32_t*>(cc_b.buf);
  const int32_t* cand_drcond = static_cast<const int32_t*>(cd_b.buf);
  const int8_t* cand_effect = static_cast<const int8_t*>(ce_b.buf);
  const int8_t* cand_pt = static_cast<const int8_t*>(cp_b.buf);
  const int8_t* cand_depth = static_cast<const int8_t*>(cdep_b.buf);
  const uint8_t* cand_valid = static_cast<const uint8_t*>(cv_b.buf);
  const int8_t* scope_sp = static_cast<const int8_t*>(sp_b.buf);
  const uint8_t* sat_cond = static_cast<const uint8_t*>(sat_b.buf);
  int8_t* fin = static_cast<int8_t*>(fin_b.buf);
  int8_t* rr = static_cast<int8_t*>(rr_b.buf);
  int8_t* wj_out = static_cast<int8_t*>(wj_b.buf);

  constexpr int kNoMatch = 0, kAllow = 1, kDeny = 2;
  constexpr int kBig = 127;

  // scope_sp/sat_cond are indexed by input id b and (for sat) condition
  // column: validate against the largest b and cond id actually referenced
  // so a mis-sized array raises instead of reading out of bounds
  {
    int32_t max_b = -1;
    for (int ba = 0; ba < BA; ba++) {
      if (ba_input[ba] < 0) {
        PyErr_SetString(PyExc_ValueError, "negative ba_input entry");
        return nullptr;
      }
      if (ba_input[ba] > max_b) max_b = ba_input[ba];
    }
    if (sp_b.len < static_cast<Py_ssize_t>(max_b + 1) * 2 * D ||
        sat_b.len < static_cast<Py_ssize_t>(max_b + 1) * C) {
      PyErr_SetString(PyExc_ValueError,
                      "scope_sp/sat buffers too small for referenced inputs");
      return nullptr;
    }
    for (Py_ssize_t idx = 0; idx < cells; idx++) {
      if (cand_cond[idx] >= C || cand_drcond[idx] >= C) {
        PyErr_SetString(PyExc_ValueError, "cand cond id out of sat range");
        return nullptr;
      }
    }
  }

  Py_BEGIN_ALLOW_THREADS
  for (int ba = 0; ba < BA; ba++) {
    const int b = ba_input[ba];
    const uint8_t* sat_row = sat_cond + static_cast<Py_ssize_t>(b) * C;
    const int8_t* sp_row = scope_sp + static_cast<Py_ssize_t>(b) * 2 * D;
    // per (k, pt) results
    for (int pt = 0; pt < 2; pt++) {
      for (int k = 0; k < K; k++) {
        int code = kNoMatch, depth_out = D, wj = -1;
        bool decided = false;
        const Py_ssize_t cell = (static_cast<Py_ssize_t>(ba) * K + k) * J;
        for (int d = 0; d < D && !decided; d++) {
          bool deny_d = false, allow_d = false;
          int deny_j = kBig, allow_j = kBig;
          for (int j = 0; j < J; j++) {
            const Py_ssize_t idx = cell + j;
            if (!cand_valid[idx]) continue;
            if (cand_pt[idx] != pt || cand_depth[idx] != d) continue;
            const int32_t cond = cand_cond[idx];
            if (cond >= 0 && !sat_row[cond]) continue;
            const int32_t dr = cand_drcond[idx];
            if (dr >= 0 && !sat_row[dr]) continue;
            const int8_t eff = cand_effect[idx];
            if (eff == deny_code) {
              deny_d = true;
              if (j < deny_j) deny_j = j;
            } else if (eff == allow_code) {
              allow_d = true;
              if (j < allow_j) allow_j = j;
            }
          }
          const bool allow_ok = allow_d && sp_row[pt * D + d] == sp_override;
          if (deny_d) {
            code = kDeny;
            depth_out = d;
            wj = deny_j;
            decided = true;
          } else if (allow_ok) {
            // winning-rule column (ISSUE 20): ALLOW decisions record their
            // first satisfied j too, mirroring the numpy/jax lattice
            code = kAllow;
            depth_out = d;
            wj = allow_j;
            decided = true;
          }
        }
        const Py_ssize_t rr_idx = ((static_cast<Py_ssize_t>(ba) * K + k) * 2 + pt) * 2;
        rr[rr_idx] = static_cast<int8_t>(code);
        rr[rr_idx + 1] = static_cast<int8_t>(depth_out);
        wj_out[(static_cast<Py_ssize_t>(ba) * K + k) * 2 + pt] =
            static_cast<int8_t>(wj);
      }
    }
    // merge: principal pass uses role 0 only; resource pass picks the first
    // role with ALLOW, else the first role with any non-NO_MATCH, else 0
    const Py_ssize_t base = static_cast<Py_ssize_t>(ba) * K;
    const int p_code = rr[(base * 2 + 0) * 2];
    const int p_depth = rr[(base * 2 + 0) * 2 + 1];
    int r_pick = 0;
    {
      int allow_k = kBig, nonmatch_k = kBig;
      for (int k = 0; k < K; k++) {
        const int code = rr[((base + k) * 2 + 1) * 2];
        if (code == kAllow && allow_k == kBig) allow_k = k;
        if (code != kNoMatch && nonmatch_k == kBig) nonmatch_k = k;
      }
      r_pick = allow_k < kBig ? allow_k : (nonmatch_k < kBig ? nonmatch_k : 0);
    }
    const int r_code = rr[((base + r_pick) * 2 + 1) * 2];
    const int r_depth = rr[((base + r_pick) * 2 + 1) * 2 + 1];
    const bool use_p = p_code != kNoMatch;
    fin[static_cast<Py_ssize_t>(ba) * 4] =
        static_cast<int8_t>(use_p ? p_code : r_code);
    fin[static_cast<Py_ssize_t>(ba) * 4 + 1] = static_cast<int8_t>(use_p ? 0 : 1);
    fin[static_cast<Py_ssize_t>(ba) * 4 + 2] =
        static_cast<int8_t>(use_p ? p_depth : r_depth);
    fin[static_cast<Py_ssize_t>(ba) * 4 + 3] =
        static_cast<int8_t>(use_p ? 0 : r_pick);
  }
  Py_END_ALLOW_THREADS
  Py_RETURN_NONE;
}

// decode_node_pool(raw_nodes, class_map, dec_value) -> list
//
// Linear decode of the bundle codec's node pool (bundle_codec._Decoder
// semantics): one forward pass, children strictly before parents, instances
// created WITHOUT running __init__ (tp_new) and fields installed with
// PyObject_GenericSetAttr (bypasses the frozen-dataclass __setattr__ guard —
// these are freshly built objects we own). Scalars pass through; tagged
// value payloads ({"$B"/"$L"/"$S"/"$M"}) go through the Python `dec_value`
// callback. Malformed structure raises ValueError, which the Python wrapper
// maps to CodecError.
namespace nodepool {

struct Names {
  PyObject *value, *name, *operand, *field, *index, *fn, *args, *target;
  PyObject *items, *entries, *init, *body, *kind, *iter_range, *iter_var;
  PyObject *step, *iter_var2, *step2, *original, *node, *expr, *children;
  PyObject *rule_activated, *condition_not_met, *constants, *ordered_variables;
};

Names* GetNames() {
  static Names* names = nullptr;
  if (!names) {
    names = new Names{
        PyUnicode_InternFromString("value"),
        PyUnicode_InternFromString("name"),
        PyUnicode_InternFromString("operand"),
        PyUnicode_InternFromString("field"),
        PyUnicode_InternFromString("index"),
        PyUnicode_InternFromString("fn"),
        PyUnicode_InternFromString("args"),
        PyUnicode_InternFromString("target"),
        PyUnicode_InternFromString("items"),
        PyUnicode_InternFromString("entries"),
        PyUnicode_InternFromString("init"),
        PyUnicode_InternFromString("body"),
        PyUnicode_InternFromString("kind"),
        PyUnicode_InternFromString("iter_range"),
        PyUnicode_InternFromString("iter_var"),
        PyUnicode_InternFromString("step"),
        PyUnicode_InternFromString("iter_var2"),
        PyUnicode_InternFromString("step2"),
        PyUnicode_InternFromString("original"),
        PyUnicode_InternFromString("node"),
        PyUnicode_InternFromString("expr"),
        PyUnicode_InternFromString("children"),
        PyUnicode_InternFromString("rule_activated"),
        PyUnicode_InternFromString("condition_not_met"),
        PyUnicode_InternFromString("constants"),
        PyUnicode_InternFromString("ordered_variables"),
    };
  }
  return names;
}

bool BadRef(Py_ssize_t i) {
  PyErr_Format(PyExc_ValueError, "bad node ref in node %zd", i);
  return false;
}

// cache[j] for child ref j (must be int < i); None passes through.
// Returns BORROWED reference or nullptr with error set.
PyObject* Child(PyObject* cache, Py_ssize_t i, PyObject* j) {
  if (j == Py_None) return Py_None;
  if (!PyLong_Check(j)) {
    BadRef(i);
    return nullptr;
  }
  Py_ssize_t idx = PyLong_AsSsize_t(j);
  if (idx < 0 || idx >= i) {
    BadRef(i);
    return nullptr;
  }
  return PyList_GET_ITEM(cache, idx);
}

// decode a value payload: scalar passes through (new ref); dict -> callback
PyObject* Value(PyObject* dec_value, PyObject* v) {
  if (v == Py_None || PyBool_Check(v) || PyLong_Check(v) ||
      PyFloat_Check(v) || PyUnicode_Check(v)) {
    Py_INCREF(v);
    return v;
  }
  return PyObject_CallFunctionObjArgs(dec_value, v, nullptr);
}

// tuple of child refs from a list payload; new reference
PyObject* ChildTuple(PyObject* cache, Py_ssize_t i, PyObject* lst) {
  if (!PyList_Check(lst)) {
    BadRef(i);
    return nullptr;
  }
  Py_ssize_t n = PyList_GET_SIZE(lst);
  PyObject* out = PyTuple_New(n);
  if (!out) return nullptr;
  for (Py_ssize_t k = 0; k < n; k++) {
    PyObject* c = Child(cache, i, PyList_GET_ITEM(lst, k));
    if (!c) {
      Py_DECREF(out);
      return nullptr;
    }
    Py_INCREF(c);
    PyTuple_SET_ITEM(out, k, c);
  }
  return out;
}

PyObject* NewInstance(PyObject* cls) {
  PyTypeObject* tp = reinterpret_cast<PyTypeObject*>(cls);
  static PyObject* empty_args = nullptr;
  if (!empty_args) empty_args = PyTuple_New(0);
  return tp->tp_new(tp, empty_args, nullptr);
}

// set attr bypassing the class __setattr__ override (frozen dataclasses)
inline int Set(PyObject* obj, PyObject* name, PyObject* value) {
  return PyObject_GenericSetAttr(obj, name, value);
}

// steal-style helper: set then drop our reference
inline int SetSteal(PyObject* obj, PyObject* name, PyObject* value) {
  if (!value) return -1;
  int rc = PyObject_GenericSetAttr(obj, name, value);
  Py_DECREF(value);
  return rc;
}

}  // namespace nodepool

PyObject* PyDecodeNodePool(PyObject*, PyObject* args) {
  PyObject* raw;
  PyObject* class_map;
  PyObject* dec_value;
  if (!PyArg_ParseTuple(args, "O!O!O", &PyList_Type, &raw, &PyDict_Type,
                        &class_map, &dec_value)) {
    return nullptr;
  }
  using namespace nodepool;
  Names* N = GetNames();
  Py_ssize_t n = PyList_GET_SIZE(raw);
  PyObject* cache = PyList_New(n);
  if (!cache) return nullptr;
  for (Py_ssize_t k = 0; k < n; k++) {
    Py_INCREF(Py_None);
    PyList_SET_ITEM(cache, k, Py_None);
  }

  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* e = PyList_GET_ITEM(raw, i);
    if (!PyList_Check(e) || PyList_GET_SIZE(e) < 2) {
      PyErr_Format(PyExc_ValueError, "malformed node %zd", i);
      Py_DECREF(cache);
      return nullptr;
    }
    PyObject* tag = PyList_GET_ITEM(e, 0);
    if (!PyUnicode_Check(tag)) {
      PyErr_Format(PyExc_ValueError, "malformed node tag at %zd", i);
      Py_DECREF(cache);
      return nullptr;
    }
    PyObject* cls = PyDict_GetItem(class_map, tag);  // borrowed
    if (!cls || !PyType_Check(cls)) {
      PyErr_Format(PyExc_ValueError, "unknown node tag at %zd", i);
      Py_DECREF(cache);
      return nullptr;
    }
    PyObject* obj = NewInstance(cls);
    if (!obj) {
      Py_DECREF(cache);
      return nullptr;
    }
    const char* t = PyUnicode_AsUTF8(tag);
    const Py_ssize_t sz = PyList_GET_SIZE(e);
    bool ok = true;
    auto item = [&](Py_ssize_t k) -> PyObject* {  // borrowed; None if short
      return k < sz ? PyList_GET_ITEM(e, k) : Py_None;
    };
    auto child_at = [&](Py_ssize_t k) -> PyObject* {
      return Child(cache, i, item(k));
    };
    if (std::strcmp(t, "sel") == 0 || std::strcmp(t, "has") == 0) {
      PyObject* op = child_at(1);
      ok = op && Set(obj, N->operand, op) == 0 &&
           Set(obj, N->field, item(2)) == 0;
    } else if (std::strcmp(t, "id") == 0) {
      ok = Set(obj, N->name, item(1)) == 0;
    } else if (std::strcmp(t, "lit") == 0) {
      ok = SetSteal(obj, N->value, Value(dec_value, item(1))) == 0;
    } else if (std::strcmp(t, "call") == 0) {
      PyObject* tgt = child_at(3);
      ok = Set(obj, N->fn, item(1)) == 0 &&
           SetSteal(obj, N->args, ChildTuple(cache, i, item(2))) == 0 &&
           tgt && Set(obj, N->target, tgt) == 0;
    } else if (std::strcmp(t, "ix") == 0) {
      PyObject* op = child_at(1);
      PyObject* ix = child_at(2);
      ok = op && ix && Set(obj, N->operand, op) == 0 &&
           Set(obj, N->index, ix) == 0;
    } else if (std::strcmp(t, "list") == 0) {
      ok = SetSteal(obj, N->items, ChildTuple(cache, i, item(1))) == 0;
    } else if (std::strcmp(t, "map") == 0) {
      PyObject* lst = item(1);
      ok = PyList_Check(lst);
      if (ok) {
        Py_ssize_t m = PyList_GET_SIZE(lst);
        PyObject* entries = PyTuple_New(m);
        ok = entries != nullptr;
        for (Py_ssize_t k = 0; ok && k < m; k++) {
          PyObject* pair = PyList_GET_ITEM(lst, k);
          if (!PyList_Check(pair) || PyList_GET_SIZE(pair) != 2) {
            ok = false;
            break;
          }
          PyObject* pk = Child(cache, i, PyList_GET_ITEM(pair, 0));
          PyObject* pv = Child(cache, i, PyList_GET_ITEM(pair, 1));
          if (!pk || !pv) {
            ok = false;
            break;
          }
          PyObject* tup = PyTuple_Pack(2, pk, pv);
          if (!tup) {
            ok = false;
            break;
          }
          PyTuple_SET_ITEM(entries, k, tup);
        }
        if (ok) {
          ok = Set(obj, N->entries, entries) == 0;
        }
        Py_XDECREF(entries);
      } else {
        BadRef(i);
      }
    } else if (std::strcmp(t, "bind") == 0) {
      PyObject* ini = child_at(2);
      PyObject* body = child_at(3);
      ok = ini && body && Set(obj, N->name, item(1)) == 0 &&
           Set(obj, N->init, ini) == 0 && Set(obj, N->body, body) == 0;
    } else if (std::strcmp(t, "comp") == 0) {
      PyObject* rng = child_at(2);
      PyObject* step = child_at(4);
      PyObject* step2 = child_at(6);
      ok = rng && step && step2 &&
           Set(obj, N->kind, item(1)) == 0 &&
           Set(obj, N->iter_range, rng) == 0 &&
           Set(obj, N->iter_var, item(3)) == 0 &&
           Set(obj, N->step, step) == 0 &&
           Set(obj, N->iter_var2, item(5)) == 0 &&
           Set(obj, N->step2, step2) == 0;
    } else if (std::strcmp(t, "E") == 0) {
      PyObject* nd = child_at(2);
      ok = nd && Set(obj, N->original, item(1)) == 0 &&
           Set(obj, N->node, nd) == 0;
    } else if (std::strcmp(t, "C") == 0) {
      PyObject* ex = child_at(2);
      ok = ex && Set(obj, N->kind, item(1)) == 0 &&
           Set(obj, N->expr, ex) == 0 &&
           SetSteal(obj, N->children, ChildTuple(cache, i, item(3))) == 0;
    } else if (std::strcmp(t, "V") == 0) {
      PyObject* ex = child_at(2);
      ok = ex && Set(obj, N->name, item(1)) == 0 &&
           Set(obj, N->expr, ex) == 0;
    } else if (std::strcmp(t, "O") == 0) {
      PyObject* ra = child_at(1);
      PyObject* cm = child_at(2);
      ok = ra && cm && Set(obj, N->rule_activated, ra) == 0 &&
           Set(obj, N->condition_not_met, cm) == 0;
    } else if (std::strcmp(t, "P") == 0) {
      ok = SetSteal(obj, N->constants, Value(dec_value, item(1))) == 0 &&
           SetSteal(obj, N->ordered_variables, ChildTuple(cache, i, item(2))) == 0;
    } else {
      PyErr_Format(PyExc_ValueError, "unknown node tag at %zd", i);
      ok = false;
    }
    if (!ok) {
      if (!PyErr_Occurred()) BadRef(i);
      Py_DECREF(obj);
      Py_DECREF(cache);
      return nullptr;
    }
    PyList_SetItem(cache, i, obj);  // steals obj, drops the None placeholder
  }
  return cache;
}

// encode_attr_columns_multi(inputs, specs, interner, missing, err,
//                           tags_u8, hi_i32, lo_i32, sid_i32, nan_u8) -> None
//
// One pass over the batch for EVERY fused column path at once. specs is a
// sequence of (mode, root, leaf) as in encode_attr_column; the output
// buffers are row-major [P, n] matrices (row p = spec p). Each input's
// principal / resource objects and their attr / jwt dicts are resolved
// ONCE and shared by all specs, so the per-input Python attribute-access
// overhead is paid once instead of P times (the packer's dominant
// memo-cold cost; VERDICT r4 item 3).
PyObject* PyEncodeAttrColumnsMulti(PyObject*, PyObject* args) {
  PyObject* inputs;
  PyObject* specs;
  PyObject* interner;
  PyObject* missing;
  PyObject* err;
  Py_buffer tags_b, hi_b, lo_b, sid_b, nan_b;
  if (!PyArg_ParseTuple(args, "OOO!OOw*w*w*w*w*", &inputs, &specs,
                        &PyDict_Type, &interner, &missing, &err, &tags_b,
                        &hi_b, &lo_b, &sid_b, &nan_b)) {
    return nullptr;
  }
  struct Bufs {
    Py_buffer *a, *b, *c, *d, *e;
    ~Bufs() {
      PyBuffer_Release(a);
      PyBuffer_Release(b);
      PyBuffer_Release(c);
      PyBuffer_Release(d);
      PyBuffer_Release(e);
    }
  } release{&tags_b, &hi_b, &lo_b, &sid_b, &nan_b};

  PyObject* seq = PySequence_Fast(inputs, "inputs must be a sequence");
  if (!seq) return nullptr;
  PyObject* spec_seq = PySequence_Fast(specs, "specs must be a sequence");
  if (!spec_seq) {
    Py_DECREF(seq);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  Py_ssize_t P = PySequence_Fast_GET_SIZE(spec_seq);
  if (tags_b.len < P * n || nan_b.len < P * n ||
      hi_b.len < static_cast<Py_ssize_t>(P * n * 4) ||
      lo_b.len < static_cast<Py_ssize_t>(P * n * 4) ||
      sid_b.len < static_cast<Py_ssize_t>(P * n * 4)) {
    Py_DECREF(spec_seq);
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "output buffers too small");
    return nullptr;
  }
  uint8_t* tags = static_cast<uint8_t*>(tags_b.buf);
  int32_t* hi = static_cast<int32_t*>(hi_b.buf);
  int32_t* lo = static_cast<int32_t*>(lo_b.buf);
  int32_t* sid = static_cast<int32_t*>(sid_b.buf);
  uint8_t* nan = static_cast<uint8_t*>(nan_b.buf);

  // spec table: mode, principal-or-resource flag, leaf object
  struct Spec {
    int mode;
    bool principal;
    PyObject* leaf;  // borrowed from spec tuple (spec_seq held)
  };
  std::vector<Spec> sp(static_cast<size_t>(P));
  bool need_p = false, need_r = false, need_jwt = false;
  bool need_p_attr = false, need_r_attr = false;
  for (Py_ssize_t p = 0; p < P; p++) {
    PyObject* item = PySequence_Fast_GET_ITEM(spec_seq, p);
    PyObject* mode_o;
    PyObject* root_o;
    PyObject* leaf_o;
    if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 3) {
      Py_DECREF(spec_seq);
      Py_DECREF(seq);
      PyErr_SetString(PyExc_TypeError, "spec must be (mode, root, leaf)");
      return nullptr;
    }
    mode_o = PyTuple_GET_ITEM(item, 0);
    root_o = PyTuple_GET_ITEM(item, 1);
    leaf_o = PyTuple_GET_ITEM(item, 2);
    long mode = PyLong_AsLong(mode_o);
    if (mode < 0 || mode > 2 || !PyUnicode_Check(root_o) ||
        !PyUnicode_Check(leaf_o)) {
      Py_DECREF(spec_seq);
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "bad spec entry");
      return nullptr;
    }
    bool is_principal =
        PyUnicode_CompareWithASCIIString(root_o, "principal") == 0;
    sp[static_cast<size_t>(p)] = {static_cast<int>(mode), is_principal, leaf_o};
    if (mode == 1) {
      need_jwt = true;
    } else if (is_principal) {
      need_p = true;
      if (mode == 0) need_p_attr = true;
    } else {
      need_r = true;
      if (mode == 0) need_r_attr = true;
    }
  }

  static PyObject* attr_name = nullptr;
  static PyObject* aux_name = nullptr;
  static PyObject* jwt_name = nullptr;
  static PyObject* principal_name = nullptr;
  static PyObject* resource_name = nullptr;
  if (!attr_name) attr_name = PyUnicode_InternFromString("attr");
  if (!aux_name) aux_name = PyUnicode_InternFromString("aux_data");
  if (!jwt_name) jwt_name = PyUnicode_InternFromString("jwt");
  if (!principal_name) principal_name = PyUnicode_InternFromString("principal");
  if (!resource_name) resource_name = PyUnicode_InternFromString("resource");

  bool fail = false;
  for (Py_ssize_t i = 0; i < n && !fail; i++) {
    PyObject* inp = PySequence_Fast_GET_ITEM(seq, i);
    // resolve shared roots once per input (owned refs, may stay null)
    PyObject* p_obj = nullptr;
    PyObject* r_obj = nullptr;
    PyObject* p_attr = nullptr;
    PyObject* r_attr = nullptr;
    PyObject* jwt = nullptr;
    if (need_p) {
      p_obj = PyObject_GetAttr(inp, principal_name);
      if (!p_obj) PyErr_Clear();
      if (need_p_attr && p_obj) {
        p_attr = PyObject_GetAttr(p_obj, attr_name);
        if (!p_attr) PyErr_Clear();
        if (p_attr && !PyDict_Check(p_attr)) Py_CLEAR(p_attr);
      }
    }
    if (need_r) {
      r_obj = PyObject_GetAttr(inp, resource_name);
      if (!r_obj) PyErr_Clear();
      if (need_r_attr && r_obj) {
        r_attr = PyObject_GetAttr(r_obj, attr_name);
        if (!r_attr) PyErr_Clear();
        if (r_attr && !PyDict_Check(r_attr)) Py_CLEAR(r_attr);
      }
    }
    if (need_jwt) {
      PyObject* aux = PyObject_GetAttr(inp, aux_name);
      if (!aux) {
        PyErr_Clear();
      } else {
        if (aux != Py_None) {
          jwt = PyObject_GetAttr(aux, jwt_name);
          if (!jwt) PyErr_Clear();
          if (jwt && !PyDict_Check(jwt)) Py_CLEAR(jwt);
        }
        Py_DECREF(aux);
      }
    }

    for (Py_ssize_t p = 0; p < P && !fail; p++) {
      const Spec& s = sp[static_cast<size_t>(p)];
      PyObject* v = nullptr;  // owned
      if (s.mode == 0) {
        PyObject* d = s.principal ? p_attr : r_attr;
        if (d) {
          PyObject* got = PyDict_GetItemWithError(d, s.leaf);  // borrowed
          if (got) {
            Py_INCREF(got);
            v = got;
          } else if (PyErr_Occurred()) {
            PyErr_Clear();
          }
        }
      } else if (s.mode == 1) {
        if (jwt) {
          PyObject* got = PyDict_GetItemWithError(jwt, s.leaf);
          if (got) {
            Py_INCREF(got);
            v = got;
          } else if (PyErr_Occurred()) {
            PyErr_Clear();
          }
        }
      } else {
        PyObject* obj = s.principal ? p_obj : r_obj;
        if (obj) {
          v = PyObject_GetAttr(obj, s.leaf);
          if (!v) PyErr_Clear();
        }
      }
      if (!v) {
        Py_INCREF(missing);
        v = missing;
      }
      Py_ssize_t at = p * n + i;
      int rc = EncodeOne(v, interner, missing, err, at, tags, hi, lo, sid, nan);
      Py_DECREF(v);
      if (rc < 0) fail = true;
    }

    Py_XDECREF(p_obj);
    Py_XDECREF(r_obj);
    Py_XDECREF(p_attr);
    Py_XDECREF(r_attr);
    Py_XDECREF(jwt);
  }
  Py_DECREF(spec_seq);
  Py_DECREF(seq);
  if (fail) return nullptr;
  Py_RETURN_NONE;
}

// -- two-level packed bitmap sweep (ruletable/index.py bitmap backend) -------
//
// Each dimension arrives as a pair of uint64 numpy arrays: `words` (bit r of
// words[r>>6] set iff row r is in the posting list) and `summary` (bit w of
// summary[w>>6] set iff words[w] != 0). The sweep ANDs the summary level to
// find candidate 64-word blocks, ANDs only the live words, and decodes set
// bits into ascending row ids — the C twin of index._sweep_numpy.

struct BitmapDims {
  std::vector<Py_buffer> bufs;       // all acquired buffers (released in dtor)
  std::vector<const uint64_t*> words;
  std::vector<Py_ssize_t> words_len; // in uint64 words
  std::vector<const uint64_t*> sums;
  std::vector<Py_ssize_t> sums_len;
  bool ok = false;

  ~BitmapDims() {
    for (auto& b : bufs) PyBuffer_Release(&b);
  }

  // sums_seq may be Py_None: small tables skip the summary level entirely
  // (a linear word AND beats six extra buffer acquisitions).
  bool Acquire(PyObject* words_seq, PyObject* sums_seq) {
    PyObject* wfast = PySequence_Fast(words_seq, "words must be a sequence");
    if (!wfast) return false;
    PyObject* sfast = nullptr;
    if (sums_seq != Py_None) {
      sfast = PySequence_Fast(sums_seq, "summaries must be a sequence");
      if (!sfast) {
        Py_DECREF(wfast);
        return false;
      }
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(wfast);
    bool good = n > 0 && (!sfast || PySequence_Fast_GET_SIZE(sfast) == n);
    if (!good) {
      PyErr_SetString(PyExc_ValueError, "words/summary dimension mismatch");
    }
    bufs.reserve((sfast ? 2 : 1) * (size_t)n);
    for (Py_ssize_t i = 0; good && i < n; i++) {
      Py_buffer wb, sb;
      if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(wfast, i), &wb,
                             PyBUF_SIMPLE) < 0) {
        good = false;
        break;
      }
      bufs.push_back(wb);
      words.push_back(static_cast<const uint64_t*>(wb.buf));
      words_len.push_back(wb.len / 8);
      if (sfast) {
        if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(sfast, i), &sb,
                               PyBUF_SIMPLE) < 0) {
          good = false;
          break;
        }
        bufs.push_back(sb);
        sums.push_back(static_cast<const uint64_t*>(sb.buf));
        sums_len.push_back(sb.len / 8);
      }
    }
    Py_DECREF(wfast);
    Py_XDECREF(sfast);
    ok = good;
    return good;
  }

  // shortest common word / summary extents (missing tails are all-zero)
  Py_ssize_t MinWords() const {
    Py_ssize_t m = words_len[0];
    for (size_t i = 1; i < words_len.size(); i++)
      if (words_len[i] < m) m = words_len[i];
    return m;
  }
  Py_ssize_t MinSums() const {
    Py_ssize_t m = sums_len[0];
    for (size_t i = 1; i < sums_len.size(); i++)
      if (sums_len[i] < m) m = sums_len[i];
    return m;
  }
};

// bitmap_sweep(words_seq, sums_seq, extra_words|None, rows|None)
//   -> (base_any, list)
// `extra` is the action dimension: legacy query semantics exclude it from the
// base-emptiness check (an empty base suppresses role-policy DENY synthesis;
// an empty action intersect does not), so it is applied after base_any is
// known. With `rows`, set bits gather rows[rid] (skipping None) instead of
// returning raw ids.
PyObject* PyBitmapSweep(PyObject*, PyObject* args) {
  PyObject *words_seq, *sums_seq, *extra_obj, *rows_obj;
  if (!PyArg_ParseTuple(args, "OOOO", &words_seq, &sums_seq, &extra_obj,
                        &rows_obj))
    return nullptr;

  if (rows_obj != Py_None && !PyList_Check(rows_obj)) {
    PyErr_SetString(PyExc_TypeError, "rows must be a list or None");
    return nullptr;
  }
  const Py_ssize_t nrows = rows_obj != Py_None ? PyList_GET_SIZE(rows_obj) : 0;

  BitmapDims dims;
  if (!dims.Acquire(words_seq, sums_seq)) return nullptr;

  Py_buffer extra_b;
  const uint64_t* extra = nullptr;
  Py_ssize_t extra_len = 0;
  if (extra_obj != Py_None) {
    if (PyObject_GetBuffer(extra_obj, &extra_b, PyBUF_SIMPLE) < 0)
      return nullptr;
    extra = static_cast<const uint64_t*>(extra_b.buf);
    extra_len = extra_b.len / 8;
  }

  PyObject* out = PyList_New(0);
  if (!out) {
    if (extra) PyBuffer_Release(&extra_b);
    return nullptr;
  }

  const Py_ssize_t L = dims.MinWords();
  const size_t nd = dims.words.size();
  bool base_any = false;
  bool fail = false;

  auto emit_word = [&](Py_ssize_t w) {
    uint64_t acc = dims.words[0][w];
    for (size_t i = 1; i < nd && acc; i++) acc &= dims.words[i][w];
    if (!acc) return;
    base_any = true;
    if (extra) acc &= (w < extra_len) ? extra[w] : 0;
    while (acc) {
      const int rbit = __builtin_ctzll(acc);
      acc &= acc - 1;
      const Py_ssize_t rid = (w << 6) + rbit;
      if (rows_obj != Py_None) {
        if (rid >= nrows) continue;  // capacity words past the row list
        PyObject* row = PyList_GET_ITEM(rows_obj, rid);  // borrowed
        if (row == Py_None) continue;
        if (PyList_Append(out, row) < 0) {
          fail = true;
          return;
        }
      } else {
        PyObject* rid_obj = PyLong_FromSsize_t(rid);
        if (!rid_obj || PyList_Append(out, rid_obj) < 0) {
          Py_XDECREF(rid_obj);
          fail = true;
          return;
        }
        Py_DECREF(rid_obj);
      }
    }
  };

  if (dims.sums.empty()) {
    for (Py_ssize_t w = 0; w < L && !fail; w++) emit_word(w);
  } else {
    const Py_ssize_t S = dims.MinSums();
    for (Py_ssize_t s = 0; s < S && !fail; s++) {
      uint64_t m = dims.sums[0][s];
      for (size_t i = 1; i < nd && m; i++) m &= dims.sums[i][s];
      while (m && !fail) {
        const int bit = __builtin_ctzll(m);
        m &= m - 1;
        const Py_ssize_t w = (s << 6) + bit;
        if (w >= L) break;  // ascending: later words in this block are past L
        emit_word(w);
      }
    }
  }

  if (extra) PyBuffer_Release(&extra_b);
  if (fail) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject* res = PyTuple_New(2);
  if (!res) {
    Py_DECREF(out);
    return nullptr;
  }
  PyTuple_SET_ITEM(res, 0, PyBool_FromLong(base_any));
  PyTuple_SET_ITEM(res, 1, out);
  return res;
}

// bitmap_any(words_seq, sums_seq) -> bool — sweep with first-hit early exit
// (exists checks).
PyObject* PyBitmapAny(PyObject*, PyObject* args) {
  PyObject *words_seq, *sums_seq;
  if (!PyArg_ParseTuple(args, "OO", &words_seq, &sums_seq)) return nullptr;

  BitmapDims dims;
  if (!dims.Acquire(words_seq, sums_seq)) return nullptr;

  const Py_ssize_t L = dims.MinWords();
  const size_t nd = dims.words.size();

  if (dims.sums.empty()) {
    for (Py_ssize_t w = 0; w < L; w++) {
      uint64_t acc = dims.words[0][w];
      for (size_t i = 1; i < nd && acc; i++) acc &= dims.words[i][w];
      if (acc) Py_RETURN_TRUE;
    }
    Py_RETURN_FALSE;
  }

  const Py_ssize_t S = dims.MinSums();
  for (Py_ssize_t s = 0; s < S; s++) {
    uint64_t m = dims.sums[0][s];
    for (size_t i = 1; i < nd && m; i++) m &= dims.sums[i][s];
    while (m) {
      const int bit = __builtin_ctzll(m);
      m &= m - 1;
      const Py_ssize_t w = (s << 6) + bit;
      if (w >= L) break;
      uint64_t acc = dims.words[0][w];
      for (size_t i = 1; i < nd && acc; i++) acc &= dims.words[i][w];
      if (acc) Py_RETURN_TRUE;
    }
  }
  Py_RETURN_FALSE;
}

// stack_pad_rows(dst, rows) — fill the 2-D+ transfer matrix `dst`
// (C-contiguous, len(rows) leading slots of row_bytes each) with the
// C-contiguous arrays in `rows`: memcpy each row's bytes into its slot and
// zero the padded tail. Replaces the per-row Python assignment loop in the
// evaluator's pad+stack pass (one call per column family per batch).
// Rows pad along their LEADING axis, so prefix-copy + zero-tail is exact.
PyObject* PyStackPadRows(PyObject*, PyObject* args) {
  PyObject *dst_obj, *rows_obj;
  if (!PyArg_ParseTuple(args, "OO", &dst_obj, &rows_obj)) return nullptr;

  Py_buffer dst_b;
  if (PyObject_GetBuffer(dst_obj, &dst_b, PyBUF_WRITABLE) < 0) return nullptr;

  PyObject* fast = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (!fast) {
    PyBuffer_Release(&dst_b);
    return nullptr;
  }
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  bool ok = true;
  if (n == 0 || dst_b.len % n != 0) {
    PyErr_SetString(PyExc_ValueError, "dst length not divisible by row count");
    ok = false;
  }
  const Py_ssize_t row_bytes = ok ? dst_b.len / n : 0;
  char* out = static_cast<char*>(dst_b.buf);
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    Py_buffer rb;
    if (PyObject_GetBuffer(PySequence_Fast_GET_ITEM(fast, i), &rb,
                           PyBUF_SIMPLE) < 0) {
      ok = false;
      break;
    }
    if (rb.len > row_bytes) {
      PyErr_SetString(PyExc_ValueError, "row larger than dst slot");
      PyBuffer_Release(&rb);
      ok = false;
      break;
    }
    char* slot = out + i * row_bytes;
    memcpy(slot, rb.buf, rb.len);
    if (rb.len < row_bytes) memset(slot + rb.len, 0, row_bytes - rb.len);
    PyBuffer_Release(&rb);
  }
  Py_DECREF(fast);
  PyBuffer_Release(&dst_b);
  if (!ok) return nullptr;
  Py_RETURN_NONE;
}

// ===========================================================================
// Front-door transport kernels (engine/ipc.py shm transport + server hot path)
//
// The multi-process front door's per-request path crosses these four pieces:
//
//   ticket_pack / ticket_unpack   CheckInput rows + relative deadline +
//                                 traceparent + waterfall carry <-> one
//                                 fixed-field-order binary frame
//   reply_pack / reply_unpack    CheckOutput effect rows + reply spec
//   ring_*                       lock-light SPSC byte ring over a shared
//                                mmap with futex wakeups (one ring per
//                                direction per front end)
//   json_loads / json_dumps      the CheckResources HTTP body parser and
//                                reply encoder (stdlib-compatible subset)
//
// Values inside frames use a small tagged binary codec (the marshal
// replacement): N/T/F, i (int64), g (bigint decimal), d (double), s (utf8
// string), b (bytes), l (list), m (dict). Field ORDER is fixed per frame
// type; values are self-describing so attr payloads stay schema-free.

PyObject* kEmptyTuple = nullptr;

struct InternTable {
  PyObject* request_id;
  PyObject* principal;
  PyObject* resource;
  PyObject* actions;
  PyObject* aux_data;
  PyObject* id;
  PyObject* roles;
  PyObject* attr;
  PyObject* policy_version;
  PyObject* scope;
  PyObject* kind;
  PyObject* jwt;
  PyObject* resource_id;
  PyObject* effective_derived_roles;
  PyObject* validation_errors;
  PyObject* outputs;
  PyObject* effective_policies;
  PyObject* effect;
  PyObject* policy;
  PyObject* src;
  PyObject* action;
  PyObject* val;
  PyObject* error;
  PyObject* path;
  PyObject* message;
  PyObject* source;
  PyObject* matched_rule;
  PyObject* rule_row_id;
};
InternTable I;

bool InitTransportStatics() {
  kEmptyTuple = PyTuple_New(0);
  if (!kEmptyTuple) return false;
#define CN_INTERN(f)                                      \
  if (!(I.f = PyUnicode_InternFromString(#f))) return false;
  CN_INTERN(request_id)
  CN_INTERN(principal)
  CN_INTERN(resource)
  CN_INTERN(actions)
  CN_INTERN(aux_data)
  CN_INTERN(id)
  CN_INTERN(roles)
  CN_INTERN(attr)
  CN_INTERN(policy_version)
  CN_INTERN(scope)
  CN_INTERN(kind)
  CN_INTERN(jwt)
  CN_INTERN(resource_id)
  CN_INTERN(effective_derived_roles)
  CN_INTERN(validation_errors)
  CN_INTERN(outputs)
  CN_INTERN(effective_policies)
  CN_INTERN(effect)
  CN_INTERN(policy)
  CN_INTERN(src)
  CN_INTERN(action)
  CN_INTERN(val)
  CN_INTERN(error)
  CN_INTERN(path)
  CN_INTERN(message)
  CN_INTERN(source)
  CN_INTERN(matched_rule)
  CN_INTERN(rule_row_id)
#undef CN_INTERN
  return true;
}

// -- tagged value codec ------------------------------------------------------

struct Buf {
  std::string s;
  void u8(uint8_t v) { s.push_back(static_cast<char>(v)); }
  void u32(uint32_t v) { s.append(reinterpret_cast<const char*>(&v), 4); }
  void u64(uint64_t v) { s.append(reinterpret_cast<const char*>(&v), 8); }
  void f64(double v) { s.append(reinterpret_cast<const char*>(&v), 8); }
  void raw(const char* p, size_t n) { s.append(p, n); }
};

struct Rd {
  const uint8_t* p;
  const uint8_t* end;
  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      PyErr_SetString(PyExc_ValueError, "truncated frame");
      return false;
    }
    return true;
  }
  bool u8(uint8_t* out) {
    if (!need(1)) return false;
    *out = *p++;
    return true;
  }
  bool u32(uint32_t* out) {
    if (!need(4)) return false;
    memcpy(out, p, 4);
    p += 4;
    return true;
  }
  bool u64(uint64_t* out) {
    if (!need(8)) return false;
    memcpy(out, p, 8);
    p += 8;
    return true;
  }
  bool f64(double* out) {
    if (!need(8)) return false;
    memcpy(out, p, 8);
    p += 8;
    return true;
  }
};

bool EncodeValue(Buf& b, PyObject* v, int depth) {
  if (depth > 64) {
    PyErr_SetString(PyExc_ValueError, "value nesting too deep for frame codec");
    return false;
  }
  if (v == Py_None) {
    b.u8('N');
    return true;
  }
  if (PyBool_Check(v)) {
    b.u8(v == Py_True ? 'T' : 'F');
    return true;
  }
  if (PyLong_Check(v)) {
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (!overflow) {
      if (x == -1 && PyErr_Occurred()) return false;
      b.u8('i');
      b.u64(static_cast<uint64_t>(x));
      return true;
    }
    PyObject* s = PyObject_Str(v);  // arbitrary-precision: decimal string
    if (!s) return false;
    Py_ssize_t n;
    const char* u = PyUnicode_AsUTF8AndSize(s, &n);
    if (!u) {
      Py_DECREF(s);
      return false;
    }
    b.u8('g');
    b.u32(static_cast<uint32_t>(n));
    b.raw(u, static_cast<size_t>(n));
    Py_DECREF(s);
    return true;
  }
  if (PyFloat_Check(v)) {
    b.u8('d');
    b.f64(PyFloat_AS_DOUBLE(v));
    return true;
  }
  if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char* u = PyUnicode_AsUTF8AndSize(v, &n);
    if (!u) return false;
    b.u8('s');
    b.u32(static_cast<uint32_t>(n));
    b.raw(u, static_cast<size_t>(n));
    return true;
  }
  if (PyBytes_Check(v)) {
    b.u8('b');
    b.u32(static_cast<uint32_t>(PyBytes_GET_SIZE(v)));
    b.raw(PyBytes_AS_STRING(v), static_cast<size_t>(PyBytes_GET_SIZE(v)));
    return true;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    PyObject* fast = PySequence_Fast(v, "sequence");
    if (!fast) return false;
    const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    b.u8('l');
    b.u32(static_cast<uint32_t>(n));
    for (Py_ssize_t i = 0; i < n; i++) {
      if (!EncodeValue(b, PySequence_Fast_GET_ITEM(fast, i), depth + 1)) {
        Py_DECREF(fast);
        return false;
      }
    }
    Py_DECREF(fast);
    return true;
  }
  if (PyDict_Check(v)) {
    b.u8('m');
    b.u32(static_cast<uint32_t>(PyDict_GET_SIZE(v)));
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &value)) {
      if (!EncodeValue(b, key, depth + 1)) return false;
      if (!EncodeValue(b, value, depth + 1)) return false;
    }
    return true;
  }
  PyErr_Format(PyExc_TypeError, "frame codec cannot encode %s",
               Py_TYPE(v)->tp_name);
  return false;
}

PyObject* DecodeValue(Rd& rd, int depth) {
  if (depth > 64) {
    PyErr_SetString(PyExc_ValueError, "frame nesting too deep");
    return nullptr;
  }
  uint8_t tag;
  if (!rd.u8(&tag)) return nullptr;
  switch (tag) {
    case 'N':
      Py_RETURN_NONE;
    case 'T':
      Py_RETURN_TRUE;
    case 'F':
      Py_RETURN_FALSE;
    case 'i': {
      uint64_t v;
      if (!rd.u64(&v)) return nullptr;
      return PyLong_FromLongLong(static_cast<long long>(v));
    }
    case 'd': {
      double v;
      if (!rd.f64(&v)) return nullptr;
      return PyFloat_FromDouble(v);
    }
    case 'g': {
      uint32_t n;
      if (!rd.u32(&n) || !rd.need(n)) return nullptr;
      std::string s(reinterpret_cast<const char*>(rd.p), n);
      rd.p += n;
      return PyLong_FromString(s.c_str(), nullptr, 10);
    }
    case 's': {
      uint32_t n;
      if (!rd.u32(&n) || !rd.need(n)) return nullptr;
      const char* q = reinterpret_cast<const char*>(rd.p);
      rd.p += n;
      return PyUnicode_DecodeUTF8(q, n, "surrogatepass");
    }
    case 'b': {
      uint32_t n;
      if (!rd.u32(&n) || !rd.need(n)) return nullptr;
      const char* q = reinterpret_cast<const char*>(rd.p);
      rd.p += n;
      return PyBytes_FromStringAndSize(q, n);
    }
    case 'l': {
      uint32_t n;
      if (!rd.u32(&n)) return nullptr;
      if (n > static_cast<size_t>(rd.end - rd.p)) {  // >=1 byte per item
        PyErr_SetString(PyExc_ValueError, "truncated frame");
        return nullptr;
      }
      PyObject* lst = PyList_New(n);
      if (!lst) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject* item = DecodeValue(rd, depth + 1);
        if (!item) {
          Py_DECREF(lst);
          return nullptr;
        }
        PyList_SET_ITEM(lst, i, item);
      }
      return lst;
    }
    case 'm': {
      uint32_t n;
      if (!rd.u32(&n)) return nullptr;
      if (n > static_cast<size_t>(rd.end - rd.p)) {
        PyErr_SetString(PyExc_ValueError, "truncated frame");
        return nullptr;
      }
      PyObject* d = PyDict_New();
      if (!d) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject* key = DecodeValue(rd, depth + 1);
        if (!key) {
          Py_DECREF(d);
          return nullptr;
        }
        PyObject* value = DecodeValue(rd, depth + 1);
        if (!value) {
          Py_DECREF(key);
          Py_DECREF(d);
          return nullptr;
        }
        const int r = PyDict_SetItem(d, key, value);
        Py_DECREF(key);
        Py_DECREF(value);
        if (r < 0) {
          Py_DECREF(d);
          return nullptr;
        }
      }
      return d;
    }
    default:
      PyErr_Format(PyExc_ValueError, "bad frame tag 0x%02x", tag);
      return nullptr;
  }
}

// GetAttr + encode, dropping the temporary.
bool EncodeAttrOf(Buf& b, PyObject* obj, PyObject* name) {
  PyObject* v = PyObject_GetAttr(obj, name);
  if (!v) return false;
  const bool ok = EncodeValue(b, v, 0);
  Py_DECREF(v);
  return ok;
}

// cls.__new__(cls): construct without running __init__/__post_init__ — the
// attrs crossing the queue were normalized at ingestion (see engine/ipc.py).
PyObject* NewInstance(PyObject* cls) {
  if (!PyType_Check(cls)) {
    PyErr_SetString(PyExc_TypeError, "expected a class");
    return nullptr;
  }
  PyTypeObject* t = reinterpret_cast<PyTypeObject*>(cls);
  return t->tp_new(t, kEmptyTuple, nullptr);
}

bool DecodeInto(Rd& rd, PyObject* obj, PyObject* name) {
  PyObject* v = DecodeValue(rd, 0);
  if (!v) return false;
  const int r = PyObject_SetAttr(obj, name, v);
  Py_DECREF(v);
  return r == 0;
}

// -- check-ticket frames -----------------------------------------------------
//
// ticket_pack(inputs, deadline_rel, traceparent, carry) -> bytes
// Layout: u8 version; value(deadline_rel); value(traceparent); u32 n;
// n x [request_id, principal(id, roles, attr, policy_version, scope),
//      resource(kind, id, attr, policy_version, scope), actions, jwt|None];
// value(carry).

// v2: reply per-action rows grew decision-provenance fields
// (matched_rule, rule_row_id, source) — ISSUE 20
constexpr uint8_t kFrameVersion = 2;

PyObject* PyTicketPack(PyObject*, PyObject* args) {
  PyObject *inputs, *deadline, *traceparent, *carry;
  if (!PyArg_ParseTuple(args, "OOOO", &inputs, &deadline, &traceparent, &carry))
    return nullptr;
  Buf b;
  b.s.reserve(512);
  b.u8(kFrameVersion);
  if (!EncodeValue(b, deadline, 0) || !EncodeValue(b, traceparent, 0))
    return nullptr;
  PyObject* fast = PySequence_Fast(inputs, "inputs must be a sequence");
  if (!fast) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  b.u32(static_cast<uint32_t>(n));
  bool ok = true;
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* inp = PySequence_Fast_GET_ITEM(fast, i);
    ok = EncodeAttrOf(b, inp, I.request_id);
    PyObject* p = ok ? PyObject_GetAttr(inp, I.principal) : nullptr;
    if (ok && !p) ok = false;
    if (ok) {
      ok = EncodeAttrOf(b, p, I.id) && EncodeAttrOf(b, p, I.roles) &&
           EncodeAttrOf(b, p, I.attr) && EncodeAttrOf(b, p, I.policy_version) &&
           EncodeAttrOf(b, p, I.scope);
    }
    Py_XDECREF(p);
    PyObject* r = ok ? PyObject_GetAttr(inp, I.resource) : nullptr;
    if (ok && !r) ok = false;
    if (ok) {
      ok = EncodeAttrOf(b, r, I.kind) && EncodeAttrOf(b, r, I.id) &&
           EncodeAttrOf(b, r, I.attr) && EncodeAttrOf(b, r, I.policy_version) &&
           EncodeAttrOf(b, r, I.scope);
    }
    Py_XDECREF(r);
    if (ok) ok = EncodeAttrOf(b, inp, I.actions);
    if (ok) {
      PyObject* aux = PyObject_GetAttr(inp, I.aux_data);
      if (!aux) {
        ok = false;
      } else {
        if (aux == Py_None) {
          b.u8('N');
        } else {
          ok = EncodeAttrOf(b, aux, I.jwt);
        }
        Py_DECREF(aux);
      }
    }
  }
  Py_DECREF(fast);
  if (!ok) return nullptr;
  if (!EncodeValue(b, carry, 0)) return nullptr;
  return PyBytes_FromStringAndSize(b.s.data(),
                                   static_cast<Py_ssize_t>(b.s.size()));
}

// ticket_unpack(data, Principal, Resource, AuxData, CheckInput)
//   -> (deadline_rel, traceparent, [CheckInput], carry)
PyObject* PyTicketUnpack(PyObject*, PyObject* args) {
  const char* data;
  Py_ssize_t len;
  PyObject *cls_p, *cls_r, *cls_aux, *cls_inp;
  if (!PyArg_ParseTuple(args, "y#OOOO", &data, &len, &cls_p, &cls_r, &cls_aux,
                        &cls_inp))
    return nullptr;
  Rd rd{reinterpret_cast<const uint8_t*>(data),
        reinterpret_cast<const uint8_t*>(data) + len};
  uint8_t ver;
  if (!rd.u8(&ver)) return nullptr;
  if (ver != kFrameVersion) {
    PyErr_Format(PyExc_ValueError, "unknown ticket frame version %d", ver);
    return nullptr;
  }
  PyObject* deadline = DecodeValue(rd, 0);
  if (!deadline) return nullptr;
  PyObject* traceparent = DecodeValue(rd, 0);
  if (!traceparent) {
    Py_DECREF(deadline);
    return nullptr;
  }
  uint32_t n = 0;
  PyObject* lst = nullptr;
  PyObject* carry = nullptr;
  bool ok = rd.u32(&n) && n <= static_cast<size_t>(rd.end - rd.p);
  if (ok) {
    lst = PyList_New(n);
    ok = lst != nullptr;
  } else if (!PyErr_Occurred()) {
    PyErr_SetString(PyExc_ValueError, "truncated frame");
  }
  for (uint32_t i = 0; ok && i < n; i++) {
    PyObject* rid = DecodeValue(rd, 0);
    PyObject* p = rid ? NewInstance(cls_p) : nullptr;
    ok = p && DecodeInto(rd, p, I.id) && DecodeInto(rd, p, I.roles) &&
         DecodeInto(rd, p, I.attr) && DecodeInto(rd, p, I.policy_version) &&
         DecodeInto(rd, p, I.scope);
    PyObject* r = ok ? NewInstance(cls_r) : nullptr;
    ok = ok && r && DecodeInto(rd, r, I.kind) && DecodeInto(rd, r, I.id) &&
         DecodeInto(rd, r, I.attr) && DecodeInto(rd, r, I.policy_version) &&
         DecodeInto(rd, r, I.scope);
    PyObject* actions = ok ? DecodeValue(rd, 0) : nullptr;
    ok = ok && actions;
    PyObject* aux = nullptr;
    if (ok) {
      PyObject* jwt = DecodeValue(rd, 0);
      if (!jwt) {
        ok = false;
      } else if (jwt == Py_None) {
        aux = Py_None;
        Py_INCREF(aux);
        Py_DECREF(jwt);
      } else {
        aux = NewInstance(cls_aux);
        ok = aux && PyObject_SetAttr(aux, I.jwt, jwt) == 0;
        Py_DECREF(jwt);
      }
    }
    PyObject* inp = ok ? NewInstance(cls_inp) : nullptr;
    ok = ok && inp && PyObject_SetAttr(inp, I.request_id, rid) == 0 &&
         PyObject_SetAttr(inp, I.principal, p) == 0 &&
         PyObject_SetAttr(inp, I.resource, r) == 0 &&
         PyObject_SetAttr(inp, I.actions, actions) == 0 &&
         PyObject_SetAttr(inp, I.aux_data, aux) == 0;
    Py_XDECREF(rid);
    Py_XDECREF(p);
    Py_XDECREF(r);
    Py_XDECREF(actions);
    Py_XDECREF(aux);
    if (ok) {
      PyList_SET_ITEM(lst, i, inp);  // steals
    } else {
      Py_XDECREF(inp);
    }
  }
  if (ok) {
    carry = DecodeValue(rd, 0);
    ok = carry != nullptr;
  }
  if (!ok) {
    Py_DECREF(deadline);
    Py_DECREF(traceparent);
    Py_XDECREF(lst);
    return nullptr;
  }
  PyObject* out = PyTuple_Pack(4, deadline, traceparent, lst, carry);
  Py_DECREF(deadline);
  Py_DECREF(traceparent);
  Py_DECREF(lst);
  Py_DECREF(carry);
  return out;
}

// -- reply frames ------------------------------------------------------------
//
// reply_pack(outputs, spec) -> bytes
// Layout: u8 version; u32 n; n x [request_id, resource_id,
//   u32 n_actions x (action, effect, policy, scope,
//                    matched_rule, rule_row_id, source),
//   effective_derived_roles,
//   u32 n_verrs x (path, message, source),
//   u32 n_outs x (src, action, val, error),
//   effective_policies]; value(spec).

PyObject* PyReplyPack(PyObject*, PyObject* args) {
  PyObject *outputs, *spec;
  if (!PyArg_ParseTuple(args, "OO", &outputs, &spec)) return nullptr;
  Buf b;
  b.s.reserve(512);
  b.u8(kFrameVersion);
  PyObject* fast = PySequence_Fast(outputs, "outputs must be a sequence");
  if (!fast) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
  b.u32(static_cast<uint32_t>(n));
  bool ok = true;
  for (Py_ssize_t i = 0; ok && i < n; i++) {
    PyObject* o = PySequence_Fast_GET_ITEM(fast, i);
    ok = EncodeAttrOf(b, o, I.request_id) && EncodeAttrOf(b, o, I.resource_id);
    if (ok) {
      PyObject* acts = PyObject_GetAttr(o, I.actions);
      ok = acts && PyDict_Check(acts);
      if (!ok && acts && !PyErr_Occurred())
        PyErr_SetString(PyExc_TypeError, "actions must be a dict");
      if (ok) {
        b.u32(static_cast<uint32_t>(PyDict_GET_SIZE(acts)));
        PyObject *key, *ae;
        Py_ssize_t pos = 0;
        while (ok && PyDict_Next(acts, &pos, &key, &ae)) {
          ok = EncodeValue(b, key, 0) && EncodeAttrOf(b, ae, I.effect) &&
               EncodeAttrOf(b, ae, I.policy) && EncodeAttrOf(b, ae, I.scope) &&
               EncodeAttrOf(b, ae, I.matched_rule) &&
               EncodeAttrOf(b, ae, I.rule_row_id) &&
               EncodeAttrOf(b, ae, I.source);
        }
      }
      Py_XDECREF(acts);
    }
    if (ok) ok = EncodeAttrOf(b, o, I.effective_derived_roles);
    if (ok) {
      PyObject* verrs = PyObject_GetAttr(o, I.validation_errors);
      PyObject* vfast =
          verrs ? PySequence_Fast(verrs, "validation_errors") : nullptr;
      ok = vfast != nullptr;
      if (ok) {
        const Py_ssize_t nv = PySequence_Fast_GET_SIZE(vfast);
        b.u32(static_cast<uint32_t>(nv));
        for (Py_ssize_t j = 0; ok && j < nv; j++) {
          PyObject* ve = PySequence_Fast_GET_ITEM(vfast, j);
          ok = EncodeAttrOf(b, ve, I.path) && EncodeAttrOf(b, ve, I.message) &&
               EncodeAttrOf(b, ve, I.source);
        }
      }
      Py_XDECREF(vfast);
      Py_XDECREF(verrs);
    }
    if (ok) {
      PyObject* oents = PyObject_GetAttr(o, I.outputs);
      PyObject* ofast = oents ? PySequence_Fast(oents, "outputs") : nullptr;
      ok = ofast != nullptr;
      if (ok) {
        const Py_ssize_t no = PySequence_Fast_GET_SIZE(ofast);
        b.u32(static_cast<uint32_t>(no));
        for (Py_ssize_t j = 0; ok && j < no; j++) {
          PyObject* oe = PySequence_Fast_GET_ITEM(ofast, j);
          ok = EncodeAttrOf(b, oe, I.src) && EncodeAttrOf(b, oe, I.action) &&
               EncodeAttrOf(b, oe, I.val) && EncodeAttrOf(b, oe, I.error);
        }
      }
      Py_XDECREF(ofast);
      Py_XDECREF(oents);
    }
    if (ok) ok = EncodeAttrOf(b, o, I.effective_policies);
  }
  Py_DECREF(fast);
  if (!ok) return nullptr;
  if (!EncodeValue(b, spec, 0)) return nullptr;
  return PyBytes_FromStringAndSize(b.s.data(),
                                   static_cast<Py_ssize_t>(b.s.size()));
}

// reply_unpack(data, CheckOutput, ActionEffect, ValidationError, OutputEntry)
//   -> ([CheckOutput], spec)
PyObject* PyReplyUnpack(PyObject*, PyObject* args) {
  const char* data;
  Py_ssize_t len;
  PyObject *cls_out, *cls_ae, *cls_ve, *cls_oe;
  if (!PyArg_ParseTuple(args, "y#OOOO", &data, &len, &cls_out, &cls_ae, &cls_ve,
                        &cls_oe))
    return nullptr;
  Rd rd{reinterpret_cast<const uint8_t*>(data),
        reinterpret_cast<const uint8_t*>(data) + len};
  uint8_t ver;
  if (!rd.u8(&ver)) return nullptr;
  if (ver != kFrameVersion) {
    PyErr_Format(PyExc_ValueError, "unknown reply frame version %d", ver);
    return nullptr;
  }
  uint32_t n = 0;
  if (!rd.u32(&n)) return nullptr;
  if (n > static_cast<size_t>(rd.end - rd.p)) {
    PyErr_SetString(PyExc_ValueError, "truncated frame");
    return nullptr;
  }
  PyObject* lst = PyList_New(n);
  if (!lst) return nullptr;
  bool ok = true;
  for (uint32_t i = 0; ok && i < n; i++) {
    PyObject* o = NewInstance(cls_out);
    ok = o && DecodeInto(rd, o, I.request_id) &&
         DecodeInto(rd, o, I.resource_id);
    if (ok) {
      uint32_t na = 0;
      ok = rd.u32(&na) && na <= static_cast<size_t>(rd.end - rd.p);
      PyObject* acts = ok ? PyDict_New() : nullptr;
      ok = ok && acts;
      for (uint32_t j = 0; ok && j < na; j++) {
        PyObject* action = DecodeValue(rd, 0);
        PyObject* ae = action ? NewInstance(cls_ae) : nullptr;
        ok = ae && DecodeInto(rd, ae, I.effect) &&
             DecodeInto(rd, ae, I.policy) && DecodeInto(rd, ae, I.scope) &&
             DecodeInto(rd, ae, I.matched_rule) &&
             DecodeInto(rd, ae, I.rule_row_id) &&
             DecodeInto(rd, ae, I.source);
        ok = ok && PyDict_SetItem(acts, action, ae) == 0;
        Py_XDECREF(action);
        Py_XDECREF(ae);
      }
      ok = ok && PyObject_SetAttr(o, I.actions, acts) == 0;
      Py_XDECREF(acts);
    }
    ok = ok && DecodeInto(rd, o, I.effective_derived_roles);
    if (ok) {
      uint32_t nv = 0;
      ok = rd.u32(&nv) && nv <= static_cast<size_t>(rd.end - rd.p);
      PyObject* verrs = ok ? PyList_New(nv) : nullptr;
      ok = ok && verrs;
      for (uint32_t j = 0; ok && j < nv; j++) {
        PyObject* ve = NewInstance(cls_ve);
        ok = ve && DecodeInto(rd, ve, I.path) &&
             DecodeInto(rd, ve, I.message) && DecodeInto(rd, ve, I.source);
        if (ok) {
          PyList_SET_ITEM(verrs, j, ve);  // steals
        } else {
          Py_XDECREF(ve);
        }
      }
      ok = ok && PyObject_SetAttr(o, I.validation_errors, verrs) == 0;
      Py_XDECREF(verrs);
    }
    if (ok) {
      uint32_t no = 0;
      ok = rd.u32(&no) && no <= static_cast<size_t>(rd.end - rd.p);
      PyObject* oents = ok ? PyList_New(no) : nullptr;
      ok = ok && oents;
      for (uint32_t j = 0; ok && j < no; j++) {
        PyObject* oe = NewInstance(cls_oe);
        ok = oe && DecodeInto(rd, oe, I.src) && DecodeInto(rd, oe, I.action) &&
             DecodeInto(rd, oe, I.val) && DecodeInto(rd, oe, I.error);
        if (ok) {
          PyList_SET_ITEM(oents, j, oe);  // steals
        } else {
          Py_XDECREF(oe);
        }
      }
      ok = ok && PyObject_SetAttr(o, I.outputs, oents) == 0;
      Py_XDECREF(oents);
    }
    ok = ok && DecodeInto(rd, o, I.effective_policies);
    if (ok) {
      PyList_SET_ITEM(lst, i, o);  // steals
    } else {
      Py_XDECREF(o);
    }
  }
  if (!ok && !PyErr_Occurred())
    PyErr_SetString(PyExc_ValueError, "truncated frame");
  PyObject* spec = ok ? DecodeValue(rd, 0) : nullptr;
  if (!spec) {
    Py_DECREF(lst);
    return nullptr;
  }
  PyObject* out = PyTuple_Pack(2, lst, spec);
  Py_DECREF(lst);
  Py_DECREF(spec);
  return out;
}

// -- shared-memory byte ring -------------------------------------------------
//
// One ring per direction per front end, over a file-backed shared mmap. The
// producer and consumer live in different processes; within a process the
// GIL serializes callers (push/pop never release it), so no extra lock is
// needed — "MPSC" on the front end is N request threads serialized by the
// GIL into the single producer role. head/tail are monotonic byte counters
// (used = head - tail); records are contiguous, with a 0xFFFFFFFF skip
// marker when a record would straddle the wrap point. Wakeups are futexes
// on two sequence words (data for the consumer, space for a full producer),
// guarded by waiter counts so the uncontended path makes no syscall.

constexpr uint32_t kRingMagic = 0x63724E31;  // "1Nrc"
constexpr size_t kRingHdrBytes = 256;
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;
constexpr size_t kRecHdrBytes = 16;  // u32 len, u32 mtype, u64 req_id

struct RingHdr {
  uint32_t magic;
  uint32_t flags;
  uint64_t capacity;
  char pad0[48];
  std::atomic<uint64_t> head;
  char pad1[56];
  std::atomic<uint64_t> tail;
  char pad2[56];
  std::atomic<uint32_t> data_seq;
  std::atomic<uint32_t> data_waiters;
  std::atomic<uint32_t> space_seq;
  std::atomic<uint32_t> space_waiters;
  std::atomic<uint64_t> pushed;
  std::atomic<uint64_t> popped;
  std::atomic<uint64_t> full_events;
  char pad3[24];
};
static_assert(sizeof(RingHdr) == kRingHdrBytes, "ring header layout");

#if defined(__linux__)
void FutexWait(std::atomic<uint32_t>* addr, uint32_t expected, int timeout_ms) {
  timespec ts;
  ts.tv_sec = timeout_ms / 1000;
  ts.tv_nsec = static_cast<long>(timeout_ms % 1000) * 1000000L;
  // non-PRIVATE: the ring is shared across processes
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAIT, expected,
          timeout_ms >= 0 ? &ts : nullptr, nullptr, 0);
}
void FutexWakeAll(std::atomic<uint32_t>* addr) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(addr), FUTEX_WAKE, INT_MAX,
          nullptr, nullptr, 0);
}
#else
void FutexWait(std::atomic<uint32_t>* addr, uint32_t expected, int timeout_ms) {
  // portable fallback: bounded sleep-poll
  const int step_us = 200;
  int waited_us = 0;
  while (addr->load(std::memory_order_acquire) == expected &&
         (timeout_ms < 0 || waited_us < timeout_ms * 1000)) {
    struct timespec ts = {0, step_us * 1000L};
    nanosleep(&ts, nullptr);
    waited_us += step_us;
  }
}
void FutexWakeAll(std::atomic<uint32_t>*) {}
#endif

RingHdr* RingFromBuffer(Py_buffer* view, bool init) {
  if (static_cast<size_t>(view->len) < kRingHdrBytes + 64) {
    PyErr_SetString(PyExc_ValueError, "ring buffer too small");
    return nullptr;
  }
  RingHdr* h = static_cast<RingHdr*>(view->buf);
  if (!init && (h->magic != kRingMagic ||
                h->capacity != static_cast<uint64_t>(view->len) - kRingHdrBytes)) {
    PyErr_SetString(PyExc_ValueError, "not an initialized ring buffer");
    return nullptr;
  }
  return h;
}

PyObject* PyRingInit(PyObject*, PyObject* args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "w*", &view)) return nullptr;
  RingHdr* h = RingFromBuffer(&view, true);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  memset(view.buf, 0, kRingHdrBytes);
  h->capacity = static_cast<uint64_t>(view.len) - kRingHdrBytes;
  h->magic = kRingMagic;
  PyBuffer_Release(&view);
  Py_RETURN_NONE;
}

PyObject* PyRingPush(PyObject*, PyObject* args) {
  Py_buffer view;
  unsigned int mtype;
  unsigned long long req_id;
  const char* payload;
  Py_ssize_t plen;
  if (!PyArg_ParseTuple(args, "w*IKy#", &view, &mtype, &req_id, &payload,
                        &plen))
    return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  char* data = static_cast<char*>(view.buf) + kRingHdrBytes;
  const uint64_t cap = h->capacity;
  const size_t need =
      kRecHdrBytes + ((static_cast<size_t>(plen) + 7) & ~static_cast<size_t>(7));
  if (need + kRecHdrBytes >= cap) {
    PyBuffer_Release(&view);
    PyErr_Format(PyExc_ValueError, "frame (%zd bytes) larger than ring", plen);
    return nullptr;
  }
  uint64_t head = h->head.load(std::memory_order_relaxed);
  const uint64_t tail = h->tail.load(std::memory_order_acquire);
  uint64_t pos = head % cap;
  const uint64_t contig = cap - pos;
  const uint64_t skip = contig < need ? contig : 0;
  if ((head - tail) + skip + need > cap) {
    h->full_events.fetch_add(1, std::memory_order_relaxed);
    PyBuffer_Release(&view);
    Py_RETURN_FALSE;
  }
  if (skip) {
    if (contig >= 4)
      memcpy(data + pos, &kWrapMarker, 4);  // consumer skips to the wrap
    head += skip;
    pos = 0;
  }
  const uint32_t len32 = static_cast<uint32_t>(plen);
  const uint32_t mtype32 = static_cast<uint32_t>(mtype);
  const uint64_t rid = static_cast<uint64_t>(req_id);
  memcpy(data + pos, &len32, 4);
  memcpy(data + pos + 4, &mtype32, 4);
  memcpy(data + pos + 8, &rid, 8);
  if (plen) memcpy(data + pos + kRecHdrBytes, payload, plen);
  h->head.store(head + need, std::memory_order_release);
  h->pushed.fetch_add(1, std::memory_order_relaxed);
  h->data_seq.fetch_add(1, std::memory_order_release);
  if (h->data_waiters.load(std::memory_order_acquire))
    FutexWakeAll(&h->data_seq);
  PyBuffer_Release(&view);
  Py_RETURN_TRUE;
}

PyObject* PyRingPop(PyObject*, PyObject* args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "w*", &view)) return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  const char* data = static_cast<const char*>(view.buf) + kRingHdrBytes;
  const uint64_t cap = h->capacity;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint32_t len = 0;
  uint64_t pos = 0;
  for (;;) {
    const uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) {
      PyBuffer_Release(&view);
      Py_RETURN_NONE;
    }
    pos = tail % cap;
    const uint64_t contig = cap - pos;
    if (contig < 4) {  // producer couldn't even fit a wrap marker
      tail += contig;
      h->tail.store(tail, std::memory_order_release);
      continue;
    }
    memcpy(&len, data + pos, 4);
    if (len == kWrapMarker) {
      tail += contig;
      h->tail.store(tail, std::memory_order_release);
      continue;
    }
    break;
  }
  const size_t need =
      kRecHdrBytes + ((static_cast<size_t>(len) + 7) & ~static_cast<size_t>(7));
  const uint64_t head = h->head.load(std::memory_order_acquire);
  if (need > cap || tail + need > head || cap - pos < need) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "corrupt ring record");
    return nullptr;
  }
  uint32_t mtype;
  uint64_t req_id;
  memcpy(&mtype, data + pos + 4, 4);
  memcpy(&req_id, data + pos + 8, 8);
  PyObject* payload = PyBytes_FromStringAndSize(data + pos + kRecHdrBytes, len);
  if (!payload) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  h->tail.store(tail + need, std::memory_order_release);
  h->popped.fetch_add(1, std::memory_order_relaxed);
  h->space_seq.fetch_add(1, std::memory_order_release);
  if (h->space_waiters.load(std::memory_order_acquire))
    FutexWakeAll(&h->space_seq);
  PyBuffer_Release(&view);
  PyObject* out = Py_BuildValue("(IKN)", mtype, (unsigned long long)req_id,
                                payload);  // N steals payload
  return out;
}

PyObject* PyRingSeq(PyObject*, PyObject* args) {
  Py_buffer view;
  int which;
  if (!PyArg_ParseTuple(args, "w*i", &view, &which)) return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  const uint32_t seq = (which ? h->space_seq : h->data_seq)
                           .load(std::memory_order_acquire);
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong(seq);
}

// ring_wait(buf, which, expected_seq, timeout_ms) -> current seq. Blocks
// (GIL released) until the chosen sequence word moves past expected_seq or
// the timeout lapses. Callers capture the seq BEFORE their emptiness check:
// a push landing in between changes the word and the wait returns at once.
PyObject* PyRingWait(PyObject*, PyObject* args) {
  Py_buffer view;
  int which, timeout_ms;
  unsigned int expected;
  if (!PyArg_ParseTuple(args, "w*iIi", &view, &which, &expected, &timeout_ms))
    return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  std::atomic<uint32_t>* seq = which ? &h->space_seq : &h->data_seq;
  std::atomic<uint32_t>* waiters = which ? &h->space_waiters : &h->data_waiters;
  uint32_t cur = seq->load(std::memory_order_acquire);
  if (cur == expected) {
    waiters->fetch_add(1, std::memory_order_acq_rel);
    Py_BEGIN_ALLOW_THREADS
    FutexWait(seq, expected, timeout_ms);
    Py_END_ALLOW_THREADS
    waiters->fetch_sub(1, std::memory_order_acq_rel);
    cur = seq->load(std::memory_order_acquire);
  }
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong(cur);
}

// ring_wake(buf, which) — shutdown aid: bump the sequence word and wake all
// waiters so a blocked consumer/producer re-checks its stop flag.
PyObject* PyRingWake(PyObject*, PyObject* args) {
  Py_buffer view;
  int which;
  if (!PyArg_ParseTuple(args, "w*i", &view, &which)) return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  std::atomic<uint32_t>* seq = which ? &h->space_seq : &h->data_seq;
  seq->fetch_add(1, std::memory_order_release);
  FutexWakeAll(seq);
  PyBuffer_Release(&view);
  Py_RETURN_NONE;
}

PyObject* PyRingStats(PyObject*, PyObject* args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "w*", &view)) return nullptr;
  RingHdr* h = RingFromBuffer(&view, false);
  if (!h) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  const uint64_t head = h->head.load(std::memory_order_acquire);
  const uint64_t tail = h->tail.load(std::memory_order_acquire);
  PyObject* out = Py_BuildValue(
      "(KKKKK)", (unsigned long long)(head - tail),
      (unsigned long long)h->capacity,
      (unsigned long long)h->pushed.load(std::memory_order_relaxed),
      (unsigned long long)h->popped.load(std::memory_order_relaxed),
      (unsigned long long)h->full_events.load(std::memory_order_relaxed));
  PyBuffer_Release(&view);
  return out;
}

// -- JSON (CheckResources hot path) ------------------------------------------
//
// A stdlib-compatible subset: json_loads matches json.loads on the request
// grammar (objects/arrays/strings with full escape handling, int vs float
// number semantics, NaN/Infinity constants, strict control-char rejection);
// json_dumps matches json.dumps defaults (ensure_ascii, ", "/": "
// separators, repr floats). Anything either side can't express raises, and
// cerbos_tpu/fastjson.py falls back to the stdlib.

void AppendUtf8(std::string& s, uint32_t c) {
  if (c < 0x80) {
    s.push_back(static_cast<char>(c));
  } else if (c < 0x800) {
    s.push_back(static_cast<char>(0xC0 | (c >> 6)));
    s.push_back(static_cast<char>(0x80 | (c & 0x3F)));
  } else if (c < 0x10000) {
    s.push_back(static_cast<char>(0xE0 | (c >> 12)));
    s.push_back(static_cast<char>(0x80 | ((c >> 6) & 0x3F)));
    s.push_back(static_cast<char>(0x80 | (c & 0x3F)));
  } else {
    s.push_back(static_cast<char>(0xF0 | (c >> 18)));
    s.push_back(static_cast<char>(0x80 | ((c >> 12) & 0x3F)));
    s.push_back(static_cast<char>(0x80 | ((c >> 6) & 0x3F)));
    s.push_back(static_cast<char>(0x80 | (c & 0x3F)));
  }
}

struct JParse {
  const char* p;
  const char* end;
  const char* start;

  void Err(const char* msg) {
    PyErr_Format(PyExc_ValueError, "%s: char %zd", msg,
                 static_cast<Py_ssize_t>(p - start));
  }
  void Ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }
  bool Lit(const char* lit, size_t n) {
    if (static_cast<size_t>(end - p) < n || memcmp(p, lit, n) != 0) {
      Err("invalid JSON literal");
      return false;
    }
    p += n;
    return true;
  }

  PyObject* String() {
    p++;  // opening quote
    std::string out;
    const char* run = p;
    while (p < end) {
      const unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        out.append(run, p - run);
        p++;
        return PyUnicode_DecodeUTF8(out.data(),
                                    static_cast<Py_ssize_t>(out.size()),
                                    "surrogatepass");
      }
      if (c < 0x20) {
        Err("invalid control character in string");
        return nullptr;
      }
      if (c != '\\') {
        p++;
        continue;
      }
      out.append(run, p - run);
      p++;
      if (p >= end) {
        Err("unterminated string escape");
        return nullptr;
      }
      const char e = *p++;
      switch (e) {
        case '"': out.push_back('"'); break;
        case '\\': out.push_back('\\'); break;
        case '/': out.push_back('/'); break;
        case 'b': out.push_back('\b'); break;
        case 'f': out.push_back('\f'); break;
        case 'n': out.push_back('\n'); break;
        case 'r': out.push_back('\r'); break;
        case 't': out.push_back('\t'); break;
        case 'u': {
          uint32_t cp;
          if (!Hex4(&cp)) return nullptr;
          if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
              p[1] == 'u') {
            const char* save = p;
            p += 2;
            uint32_t lo;
            if (!Hex4(&lo)) return nullptr;
            if (lo >= 0xDC00 && lo <= 0xDFFF) {
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            } else {
              p = save;  // not a low surrogate: emit the lone high one
            }
          }
          AppendUtf8(out, cp);  // lone surrogates pass through surrogatepass
          break;
        }
        default:
          p--;
          Err("invalid string escape");
          return nullptr;
      }
      run = p;
    }
    Err("unterminated string");
    return nullptr;
  }

  bool Hex4(uint32_t* out) {
    if (end - p < 4) {
      Err("truncated \\u escape");
      return false;
    }
    uint32_t v = 0;
    for (int i = 0; i < 4; i++) {
      const char c = p[i];
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else {
        Err("invalid \\u escape");
        return false;
      }
    }
    p += 4;
    *out = v;
    return true;
  }

  PyObject* Number() {
    const char* tok = p;
    bool is_float = false;
    if (p < end && *p == '-') p++;
    if (p < end && *p == '0') {
      p++;
    } else if (p < end && *p >= '1' && *p <= '9') {
      while (p < end && *p >= '0' && *p <= '9') p++;
    } else {
      Err("invalid number");
      return nullptr;
    }
    if (p < end && *p == '.') {
      is_float = true;
      p++;
      if (p >= end || *p < '0' || *p > '9') {
        Err("invalid number");
        return nullptr;
      }
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      is_float = true;
      p++;
      if (p < end && (*p == '+' || *p == '-')) p++;
      if (p >= end || *p < '0' || *p > '9') {
        Err("invalid number");
        return nullptr;
      }
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    std::string s(tok, p - tok);
    if (is_float) {
      const double d = PyOS_string_to_double(s.c_str(), nullptr, nullptr);
      if (d == -1.0 && PyErr_Occurred()) return nullptr;
      return PyFloat_FromDouble(d);
    }
    return PyLong_FromString(s.c_str(), nullptr, 10);
  }

  PyObject* Value(int depth) {
    if (depth > 500) {
      PyErr_SetString(PyExc_ValueError, "JSON nesting too deep");
      return nullptr;
    }
    Ws();
    if (p >= end) {
      Err("unexpected end of JSON");
      return nullptr;
    }
    switch (*p) {
      case '{': {
        p++;
        PyObject* d = PyDict_New();
        if (!d) return nullptr;
        Ws();
        if (p < end && *p == '}') {
          p++;
          return d;
        }
        for (;;) {
          Ws();
          if (p >= end || *p != '"') {
            Err("expecting property name in double quotes");
            Py_DECREF(d);
            return nullptr;
          }
          PyObject* k = String();
          if (!k) {
            Py_DECREF(d);
            return nullptr;
          }
          Ws();
          if (p >= end || *p != ':') {
            Err("expecting ':' delimiter");
            Py_DECREF(k);
            Py_DECREF(d);
            return nullptr;
          }
          p++;
          PyObject* v = Value(depth + 1);
          if (!v) {
            Py_DECREF(k);
            Py_DECREF(d);
            return nullptr;
          }
          const int r = PyDict_SetItem(d, k, v);
          Py_DECREF(k);
          Py_DECREF(v);
          if (r < 0) {
            Py_DECREF(d);
            return nullptr;
          }
          Ws();
          if (p < end && *p == ',') {
            p++;
            continue;
          }
          if (p < end && *p == '}') {
            p++;
            return d;
          }
          Err("expecting ',' delimiter");
          Py_DECREF(d);
          return nullptr;
        }
      }
      case '[': {
        p++;
        PyObject* lst = PyList_New(0);
        if (!lst) return nullptr;
        Ws();
        if (p < end && *p == ']') {
          p++;
          return lst;
        }
        for (;;) {
          PyObject* v = Value(depth + 1);
          if (!v) {
            Py_DECREF(lst);
            return nullptr;
          }
          const int r = PyList_Append(lst, v);
          Py_DECREF(v);
          if (r < 0) {
            Py_DECREF(lst);
            return nullptr;
          }
          Ws();
          if (p < end && *p == ',') {
            p++;
            continue;
          }
          if (p < end && *p == ']') {
            p++;
            return lst;
          }
          Err("expecting ',' delimiter");
          Py_DECREF(lst);
          return nullptr;
        }
      }
      case '"':
        return String();
      case 't':
        if (!Lit("true", 4)) return nullptr;
        Py_RETURN_TRUE;
      case 'f':
        if (!Lit("false", 5)) return nullptr;
        Py_RETURN_FALSE;
      case 'n':
        if (!Lit("null", 4)) return nullptr;
        Py_RETURN_NONE;
      case 'N':
        if (!Lit("NaN", 3)) return nullptr;
        return PyFloat_FromDouble(Py_NAN);
      case 'I':
        if (!Lit("Infinity", 8)) return nullptr;
        return PyFloat_FromDouble(Py_HUGE_VAL);
      case '-':
        if (end - p >= 2 && p[1] == 'I') {
          if (!Lit("-Infinity", 9)) return nullptr;
          return PyFloat_FromDouble(-Py_HUGE_VAL);
        }
        return Number();
      default:
        if (*p >= '0' && *p <= '9') return Number();
        Err("expecting value");
        return nullptr;
    }
  }
};

PyObject* PyJsonLoads(PyObject*, PyObject* args) {
  Py_buffer view;
  if (!PyArg_ParseTuple(args, "s*", &view)) return nullptr;
  JParse jp;
  jp.start = jp.p = static_cast<const char*>(view.buf);
  jp.end = jp.p + view.len;
  PyObject* out = jp.Value(0);
  if (out) {
    jp.Ws();
    if (jp.p != jp.end) {
      jp.Err("extra data");
      Py_CLEAR(out);
    }
  }
  PyBuffer_Release(&view);
  return out;
}

bool JsonDumpValue(std::string& out, PyObject* v, int depth) {
  if (depth > 500) {
    PyErr_SetString(PyExc_ValueError, "JSON nesting too deep (circular?)");
    return false;
  }
  if (v == Py_None) {
    out += "null";
    return true;
  }
  if (v == Py_True) {
    out += "true";
    return true;
  }
  if (v == Py_False) {
    out += "false";
    return true;
  }
  if (PyLong_Check(v)) {
    PyObject* s = PyObject_Str(v);
    if (!s) return false;
    Py_ssize_t n;
    const char* u = PyUnicode_AsUTF8AndSize(s, &n);
    if (!u) {
      Py_DECREF(s);
      return false;
    }
    out.append(u, n);
    Py_DECREF(s);
    return true;
  }
  if (PyFloat_Check(v)) {
    const double d = PyFloat_AS_DOUBLE(v);
    if (d != d) {
      out += "NaN";
    } else if (d == Py_HUGE_VAL) {
      out += "Infinity";
    } else if (d == -Py_HUGE_VAL) {
      out += "-Infinity";
    } else {
      char* s = PyOS_double_to_string(d, 'r', 0, Py_DTSF_ADD_DOT_0, nullptr);
      if (!s) return false;
      out += s;
      PyMem_Free(s);
    }
    return true;
  }
  if (PyUnicode_Check(v)) {
    if (PyUnicode_READY(v) < 0) return false;
    const int kind = PyUnicode_KIND(v);
    const void* data = PyUnicode_DATA(v);
    const Py_ssize_t n = PyUnicode_GET_LENGTH(v);
    out.push_back('"');
    char esc[16];
    for (Py_ssize_t i = 0; i < n; i++) {
      const Py_UCS4 c = PyUnicode_READ(kind, data, i);
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20 || c > 0x7E) {  // ensure_ascii
            if (c > 0xFFFF) {
              const Py_UCS4 x = c - 0x10000;
              snprintf(esc, sizeof esc, "\\u%04x\\u%04x",
                       0xD800 + (x >> 10), 0xDC00 + (x & 0x3FF));
            } else {
              snprintf(esc, sizeof esc, "\\u%04x", c);
            }
            out += esc;
          } else {
            out.push_back(static_cast<char>(c));
          }
      }
    }
    out.push_back('"');
    return true;
  }
  if (PyList_Check(v) || PyTuple_Check(v)) {
    PyObject* fast = PySequence_Fast(v, "sequence");
    if (!fast) return false;
    const Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    out.push_back('[');
    for (Py_ssize_t i = 0; i < n; i++) {
      if (i) out += ", ";
      if (!JsonDumpValue(out, PySequence_Fast_GET_ITEM(fast, i), depth + 1)) {
        Py_DECREF(fast);
        return false;
      }
    }
    Py_DECREF(fast);
    out.push_back(']');
    return true;
  }
  if (PyDict_Check(v)) {
    out.push_back('{');
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    bool first = true;
    while (PyDict_Next(v, &pos, &key, &value)) {
      if (!PyUnicode_Check(key)) {
        // non-str keys (int/bool/None coercion): stdlib fallback handles it
        PyErr_SetString(PyExc_TypeError, "JSON object keys must be str");
        return false;
      }
      if (!first) out += ", ";
      first = false;
      if (!JsonDumpValue(out, key, depth + 1)) return false;
      out += ": ";
      if (!JsonDumpValue(out, value, depth + 1)) return false;
    }
    out.push_back('}');
    return true;
  }
  PyErr_Format(PyExc_TypeError, "Object of type %s is not JSON serializable",
               Py_TYPE(v)->tp_name);
  return false;
}

PyObject* PyJsonDumps(PyObject*, PyObject* args) {
  PyObject* v;
  if (!PyArg_ParseTuple(args, "O", &v)) return nullptr;
  std::string out;
  out.reserve(256);
  if (!JsonDumpValue(out, v, 0)) return nullptr;
  return PyBytes_FromStringAndSize(out.data(),
                                   static_cast<Py_ssize_t>(out.size()));
}

PyMethodDef kMethods[] = {
    {"glob_match", PyGlobMatch, METH_VARARGS,
     "glob_match(pattern, value) -> bool — gobwas-style glob with ':' separator"},
    {"glob_match_many", PyGlobMatchMany, METH_VARARGS,
     "glob_match_many(patterns, value) -> list[int] of matching indices"},
    {"encode_double_keys", PyEncodeDoubleKeys, METH_VARARGS,
     "encode_double_keys(f64 buffer) -> (hi_i32_bytes, lo_i32_bytes, nan_u8_bytes)"},
    {"encode_column", PyEncodeColumn, METH_VARARGS,
     "encode_column(values, interner, missing, err, tags, hi, lo, sid, nan)"},
    {"encode_attr_column", PyEncodeAttrColumn, METH_VARARGS,
     "encode_attr_column(inputs, mode, root, leaf, interner, missing, err, "
     "tags, hi, lo, sid, nan) — fused gather + encode"},
    {"encode_attr_columns_multi", PyEncodeAttrColumnsMulti, METH_VARARGS,
     "encode_attr_columns_multi(inputs, specs, interner, missing, err, "
     "tags[P,n], hi, lo, sid, nan) — all fused columns in one batch pass"},
    {"encode_list_column", PyEncodeListColumn, METH_VARARGS,
     "encode_list_column(inputs, mode, root, leaf, interner, missing, state) "
     "-> (width, sids_bytes) — fused gather + intern for string lists"},
    {"resolve_effects", PyResolveEffects, METH_VARARGS,
     "resolve_effects(...) — fused effect-resolution lattice over the "
     "candidate tensors (numpy-path replacement for _compute's second half)"},
    {"decode_node_pool", PyDecodeNodePool, METH_VARARGS,
     "decode_node_pool(raw_nodes, class_map, dec_value) -> list — linear "
     "decode of the bundle codec node pool without running __init__"},
    {"bitmap_sweep", PyBitmapSweep, METH_VARARGS,
     "bitmap_sweep(words_seq, sums_seq, extra|None, rows|None) -> "
     "(base_any, list) — fused two-level packed-bitmap AND sweep"},
    {"bitmap_any", PyBitmapAny, METH_VARARGS,
     "bitmap_any(words_seq, sums_seq) -> bool — packed-bitmap AND with "
     "first-hit early exit"},
    {"stack_pad_rows", PyStackPadRows, METH_VARARGS,
     "stack_pad_rows(dst, rows) — memcpy each contiguous row into its "
     "padded slot of dst and zero the tail (fused pad+stack fill)"},
    {"ticket_pack", PyTicketPack, METH_VARARGS,
     "ticket_pack(inputs, deadline_rel, traceparent, carry) -> bytes — "
     "CheckInput rows into one binary ticket frame"},
    {"ticket_unpack", PyTicketUnpack, METH_VARARGS,
     "ticket_unpack(data, Principal, Resource, AuxData, CheckInput) -> "
     "(deadline_rel, traceparent, inputs, carry)"},
    {"reply_pack", PyReplyPack, METH_VARARGS,
     "reply_pack(outputs, spec) -> bytes — CheckOutput effect rows + reply "
     "spec into one binary reply frame"},
    {"reply_unpack", PyReplyUnpack, METH_VARARGS,
     "reply_unpack(data, CheckOutput, ActionEffect, ValidationError, "
     "OutputEntry) -> (outputs, spec)"},
    {"ring_init", PyRingInit, METH_VARARGS,
     "ring_init(buf) — zero the header and stamp magic/capacity"},
    {"ring_push", PyRingPush, METH_VARARGS,
     "ring_push(buf, mtype, req_id, payload) -> bool — False when full"},
    {"ring_pop", PyRingPop, METH_VARARGS,
     "ring_pop(buf) -> (mtype, req_id, payload) | None"},
    {"ring_seq", PyRingSeq, METH_VARARGS,
     "ring_seq(buf, which) -> int — current data(0)/space(1) sequence word"},
    {"ring_wait", PyRingWait, METH_VARARGS,
     "ring_wait(buf, which, expected_seq, timeout_ms) -> int — futex wait "
     "until the sequence word moves; returns the current value"},
    {"ring_wake", PyRingWake, METH_VARARGS,
     "ring_wake(buf, which) — bump the sequence word and wake all waiters"},
    {"ring_stats", PyRingStats, METH_VARARGS,
     "ring_stats(buf) -> (used, capacity, pushed, popped, full_events)"},
    {"json_loads", PyJsonLoads, METH_VARARGS,
     "json_loads(bytes|str) -> obj — stdlib-compatible JSON parse"},
    {"json_dumps", PyJsonDumps, METH_VARARGS,
     "json_dumps(obj) -> bytes — stdlib-default-compatible JSON encode"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "cerbos_native",
    "Native host-path helpers for cerbos_tpu", -1, kMethods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_cerbos_native(void) {
  if (!InitTransportStatics()) return nullptr;
  return PyModule_Create(&kModule);
}
