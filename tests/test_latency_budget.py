"""Latency-budget waterfall, goodput accounting, and pressure signals.

Covers the PR's acceptance surface at the unit/integration level:

- stage-sum reconciliation: the recorded stages tile each request's wall
  clock (the ``mark``/``add`` cursor invariant), in-process and through a
  real batcher;
- cross-process clock anchoring: only RELATIVE values cross the IPC hop,
  so an arbitrary monotonic-clock skew between front end and batcher
  cancels out of the reassembled waterfall;
- goodput vs throughput: ``cerbos_tpu_decisions_total{outcome}`` splits
  under a ``wedge_after`` chaos drill (expired requests count against
  throughput, not goodput);
- slow-request ring capture with the ``?shard=`` filter;
- pressure under backlog: the queue component rises before deadlines die,
  and the high-water crossing leaves a flight-recorder breadcrumb;

across all three topologies: single batcher, the frontends ticket queue
(``BatcherIpcServer``/``RemoteBatcherClient`` in-process pair), and the
sharded pool.
"""

import time

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import budget as budget_mod
from cerbos_tpu.engine import flight
from cerbos_tpu.engine.batcher import BatchingEvaluator, DeadlineExceeded
from cerbos_tpu.engine.budget import (
    OUTCOME_EXPIRED,
    OUTCOME_MET,
    OUTCOME_ORACLE,
    STAGE_ADMISSION,
    STAGE_INGRESS_PARSE,
    STAGE_IPC_ENCODE,
    STAGE_IPC_RETURN,
    STAGE_QUEUE_WAIT,
    STAGE_REPLY_ENCODE,
    STAGE_SETTLE,
    STAGE_TRANSIT,
    STAGES,
    Waterfall,
)
from cerbos_tpu.engine.health import DeviceHealth
from cerbos_tpu.engine.pressure import HIGH_WATER, PressureMonitor
from cerbos_tpu.engine.shards import ShardedBatchingEvaluator
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
        request_id=f"rq{i}",
    )


class OracleEvaluator:
    """CPU-oracle-backed evaluator with the streaming surface (no jax)."""

    def __init__(self, rt, submit_delay_s: float = 0.0):
        self.rule_table = rt
        self.schema_mgr = None
        self.submit_delay_s = submit_delay_s
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return [check_input(self.rule_table, i, params or EvalParams()) for i in inputs]

    def submit(self, inputs, params=None):
        if self.submit_delay_s:
            time.sleep(self.submit_delay_s)
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


@pytest.fixture()
def rt():
    return table()


@pytest.fixture()
def tracker():
    trk = budget_mod.tracker()
    prev = (trk.enabled, trk.slow_threshold_s, trk._ring.maxlen)
    trk.configure(enabled=True)
    trk.reset()
    yield trk
    trk.configure(
        enabled=prev[0], slow_threshold_ms=prev[1] * 1000, slow_capacity=prev[2]
    )
    trk.reset()


def stage_names(wf):
    return [s for s, _ in wf.stages]


def finish_like_server(trk, wf, fn):
    """The server layer's outcome classification, distilled for unit tests."""
    try:
        out = fn()
    except DeadlineExceeded:
        trk.finish(wf, OUTCOME_EXPIRED)
        return None
    trk.finish(
        wf,
        OUTCOME_ORACLE if wf is not None and wf.served_by == "oracle" else OUTCOME_MET,
        final_stage=STAGE_REPLY_ENCODE,
    )
    return out


class TestWaterfallRecord:
    def test_marks_tile_wall_clock(self):
        wf = Waterfall()
        wf.mark(STAGE_INGRESS_PARSE)
        time.sleep(0.002)
        wf.mark(STAGE_ADMISSION)
        assert wf.attributed() == pytest.approx(wf.age(now=wf._last), abs=1e-9)

    def test_add_advances_cursor_so_marks_book_residual(self):
        wf = Waterfall(t0=100.0)
        wf.add("pack", 0.010)
        wf.add("device", 0.020)
        # external durations moved the cursor to t0+0.030; a mark at
        # t0+0.050 books only the 0.020 residual
        wf.mark(STAGE_SETTLE, now=100.050)
        assert dict(wf.stages)[STAGE_SETTLE] == pytest.approx(0.020)
        assert wf.attributed() == pytest.approx(0.050)

    def test_snapshot_carries_trace_outcome_fields(self):
        wf = Waterfall(trace_id="t-123", deadline=time.monotonic() + 1.0)
        wf.shard = 2
        wf.note_fallback("breaker_open")
        wf.mark("oracle")
        snap = wf.snapshot()
        assert snap["trace_id"] == "t-123"
        assert snap["shard"] == 2
        assert snap["served_by"] == "oracle"
        assert snap["fallback_reason"] == "breaker_open"
        assert snap["budget_remaining_ms"] > 0


class TestCrossProcessAnchoring:
    def test_carry_resume_books_transit_from_unattributed_age(self):
        spec = (0.010, 0.004)  # 10ms old, 4ms already attributed
        wf = Waterfall.from_carry(spec, trace_id="t-x")
        stages = dict(wf.stages)
        assert stages[STAGE_TRANSIT] == pytest.approx(0.006, abs=2e-3)
        assert wf.age() == pytest.approx(0.010, abs=2e-3)

    def test_clock_skew_cancels(self):
        """Both processes only ever exchange RELATIVE values, so the
        reassembled waterfall is identical no matter how far apart the two
        monotonic clocks sit. Simulated with explicit clock offsets."""
        fe_now = 1000.0  # front-end clock
        wf_fe = Waterfall(t0=fe_now)
        wf_fe.mark(STAGE_INGRESS_PARSE, now=fe_now + 0.001)
        wf_fe.mark(STAGE_IPC_ENCODE, now=fe_now + 0.003)
        carry = wf_fe.carry(now=fe_now + 0.005)  # 2ms in flight so far
        assert carry == (pytest.approx(0.005), pytest.approx(0.003))

        # batcher clock sits 9000s away; only the carried age matters
        wf_b = Waterfall.from_carry(carry)
        stages_b = dict(wf_b.stages)
        assert stages_b[STAGE_TRANSIT] == pytest.approx(0.002, abs=2e-3)
        wf_b.mark(STAGE_QUEUE_WAIT)
        reply = wf_b.reply_spec()

        # front end splices the batcher stages and books the return residual
        wf_fe.splice_reply(reply, now=fe_now + 0.009)
        names = stage_names(wf_fe)
        assert names[:2] == [STAGE_INGRESS_PARSE, STAGE_IPC_ENCODE]
        assert STAGE_TRANSIT in names and STAGE_QUEUE_WAIT in names
        assert names[-1] == STAGE_IPC_RETURN
        # reconciliation: every recorded stage tiles the front-end wall clock
        assert wf_fe.attributed() == pytest.approx(0.009, abs=3e-3)

    def test_malformed_carry_resumes_to_none(self, tracker):
        assert tracker.resume("not-a-spec") is None
        assert tracker.resume(None) is None


class TestSingleBatcherTopology:
    def test_stage_sum_reconciles_through_batcher(self, rt, tracker):
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        try:
            t0 = time.monotonic()
            wf = tracker.start(trace_id="t-single")
            out = finish_like_server(tracker, wf, lambda: b.check([inp(1)], wf=wf))
            wall = time.monotonic() - t0
            assert out is not None
            names = stage_names(wf)
            assert set(names) <= set(STAGES)
            for want in (STAGE_ADMISSION, STAGE_QUEUE_WAIT, STAGE_SETTLE, STAGE_REPLY_ENCODE):
                assert want in names, names
            # >=95% of the request's wall clock attributed to named stages
            assert wf.attributed() >= 0.95 * (wall - 0.001)
            assert wf.attributed() <= wall + 0.005
            assert wf.shard == 0
        finally:
            b.close()

    def test_budget_sampled_at_enqueue_and_device_submit(self, rt, tracker):
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        try:
            before_enq = tracker.m_budget.labels(("enqueue", "0")).snapshot()[2]
            before_sub = tracker.m_budget.labels(("device_submit", "0")).snapshot()[2]
            wf = tracker.start(deadline=time.monotonic() + 5.0)
            b.check([inp(2)], deadline=time.monotonic() + 5.0, wf=wf)
            assert tracker.m_budget.labels(("enqueue", "0")).snapshot()[2] == before_enq + 1
            assert (
                tracker.m_budget.labels(("device_submit", "0")).snapshot()[2]
                == before_sub + 1
            )
        finally:
            b.close()

    def test_breaker_open_notes_oracle_fallback(self, rt, tracker):
        health = DeviceHealth(failure_threshold=1)
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0, health=health)
        try:
            health.record_failure()  # threshold=1: trips the breaker open
            wf = tracker.start()
            finish_like_server(tracker, wf, lambda: b.check([inp(3)], wf=wf))
            assert wf.served_by == "oracle"
            assert wf.fallback_reason == "breaker_open"
            assert "oracle" in stage_names(wf)
        finally:
            b.close()


class TestGoodputUnderWedge:
    def test_expired_counts_against_throughput_not_goodput(self, rt, tracker):
        from cerbos_tpu.engine.faults import FaultInjector

        # the first request's submit+collect succeed (2 device calls), then
        # the device wedges: later requests blow their deadlines and must
        # land in outcome=expired
        wedged = FaultInjector(OracleEvaluator(rt), "wedge_after:2,wedge_sleep_s:1")
        b = BatchingEvaluator(wedged, max_wait_ms=1.0, min_batch_to_wait=1)
        vec = tracker.m_decisions
        before = {k: vec.get(("check", k)) for k in (OUTCOME_MET, OUTCOME_EXPIRED)}
        try:
            wf = tracker.start()
            assert finish_like_server(tracker, wf, lambda: b.check([inp(1)], wf=wf))
            for i in range(2):
                deadline = time.monotonic() + 0.2
                wf = tracker.start(deadline=deadline)
                out = finish_like_server(
                    tracker, wf, lambda: b.check([inp(10 + i)], deadline=deadline, wf=wf)
                )
                assert out is None  # deadline expired while the device wedged
        finally:
            b.close()
        met = vec.get(("check", OUTCOME_MET)) - before[OUTCOME_MET]
        expired = vec.get(("check", OUTCOME_EXPIRED)) - before[OUTCOME_EXPIRED]
        assert met == 1
        assert expired == 2


class TestSlowRing:
    def test_captures_above_threshold_with_shard_filter(self, rt, tracker):
        tracker.configure(slow_threshold_ms=0.0, slow_capacity=8)
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0, shard_id=3)
        try:
            wf = tracker.start(trace_id="t-slow")
            finish_like_server(tracker, wf, lambda: b.check([inp(5)], wf=wf))
        finally:
            b.close()
        dump = tracker.slow_dump()
        assert dump["requests"], dump
        entry = dump["requests"][0]
        assert entry["trace_id"] == "t-slow"
        assert entry["outcome"] == OUTCOME_MET
        assert entry["shard"] == 3
        assert any(s == STAGE_QUEUE_WAIT for s, _ in entry["stages"])
        # shard filter: matching shard keeps the entry, others drop it
        assert tracker.slow_dump(shard=3)["requests"]
        assert not tracker.slow_dump(shard=7)["requests"]

    def test_ring_is_bounded(self, tracker):
        tracker.configure(slow_threshold_ms=0.0, slow_capacity=4)
        for i in range(10):
            wf = tracker.start(trace_id=f"t{i}")
            wf.mark(STAGE_ADMISSION)
            tracker.finish(wf, OUTCOME_MET)
        assert len(tracker.slow_dump()["requests"]) == 4

    def test_disabled_tracker_still_counts_decisions(self, tracker):
        tracker.configure(enabled=False)
        before = tracker.m_decisions.get(("check", OUTCOME_MET))
        assert tracker.start() is None
        tracker.finish(None, OUTCOME_MET)
        tracker.count(OUTCOME_MET)
        assert tracker.m_decisions.get(("check", OUTCOME_MET)) == before + 2
        assert not tracker.slow_dump()["requests"]


class TestFrontendsTopology:
    @pytest.mark.parametrize("transport", ["shm", "uds"])
    def test_waterfall_crosses_ticket_queue(self, tmp_path, rt, tracker, transport):
        """Attribution must hold on BOTH data planes: the shm frame rings
        (native codec, ipc_encode marked before the carry is cut) and the
        uds marshal fallback tile the front end's wall clock identically."""
        from cerbos_tpu import native
        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient

        if transport == "shm" and native.get() is None:
            pytest.skip("native module unavailable: shm plane cannot grant")
        batcher = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        server = BatcherIpcServer(str(tmp_path / "b.sock"), batcher, transport=transport)
        server.start()
        client = RemoteBatcherClient(
            server.socket_path,
            rt,
            worker_label="fe-test",
            status_poll_s=0.05,
            transport=transport,
        )
        try:
            deadline = time.monotonic() + 10.0
            assert client._connected.wait(5.0)
            assert client.transport == transport
            t0 = time.monotonic()
            wf = tracker.start(trace_id="t-fe", deadline=deadline)
            out = finish_like_server(
                tracker, wf, lambda: client.check([inp(1)], deadline=deadline, wf=wf)
            )
            wall = time.monotonic() - t0
            assert out is not None
            names = stage_names(wf)
            # front-end stages, batcher stages, and the return residual all
            # present, in one record (no settle: the ticket server rides the
            # async path, so the reply spec is cut on the drain loop)
            for want in (
                STAGE_IPC_ENCODE,
                STAGE_TRANSIT,
                STAGE_ADMISSION,
                STAGE_QUEUE_WAIT,
                STAGE_IPC_RETURN,
                STAGE_REPLY_ENCODE,
            ):
                assert want in names, names
            assert set(names) <= set(STAGES)
            # reconciliation across the process boundary: attribution covers
            # the front end's measured wall clock
            assert wf.attributed() >= 0.95 * (wall - 0.001)
            assert wf.attributed() <= wall + 0.005
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_oracle_fallback_crosses_reply_spec(self, tmp_path, rt, tracker):
        """A batcher-side oracle serve must be visible to the front end's
        outcome classification via the reply spec."""
        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient

        health = DeviceHealth(failure_threshold=1)
        batcher = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0, health=health)
        server = BatcherIpcServer(str(tmp_path / "b.sock"), batcher)
        server.start()
        client = RemoteBatcherClient(
            server.socket_path, rt, worker_label="fe-test", status_poll_s=0.05
        )
        try:
            assert client._connected.wait(5.0)
            health.record_failure()  # threshold=1: trips the breaker open
            wf = tracker.start()
            out = finish_like_server(tracker, wf, lambda: client.check([inp(2)], wf=wf))
            assert out is not None
            assert wf.served_by == "oracle"
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_ipc_slow_and_pressure_snapshots(self, tmp_path, rt, tracker):
        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient

        tracker.configure(slow_threshold_ms=0.0)
        batcher = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        server = BatcherIpcServer(str(tmp_path / "b.sock"), batcher)
        server.start()
        client = RemoteBatcherClient(
            server.socket_path, rt, worker_label="fe-test", status_poll_s=0.05
        )
        try:
            assert client._connected.wait(5.0)
            wf = tracker.start(trace_id="t-ring")
            finish_like_server(tracker, wf, lambda: client.check([inp(3)], wf=wf))
            # in-process pair shares one tracker, so the ring holds the entry;
            # the frames themselves must round-trip the dump + pressure sample
            slow = client.fetch_slow()
            assert slow["requests"], slow
            assert "pid" in slow
            pres = client.fetch_pressure()
            assert "score" in pres and "components" in pres
        finally:
            client.close()
            server.close()
            batcher.close()


class TestShardedTopology:
    def test_waterfall_carries_lane_shard_id(self, rt, tracker):
        lanes = [
            BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0, shard_id=i)
            for i in range(2)
        ]
        pool = ShardedBatchingEvaluator(lanes, routing="round_robin")
        try:
            seen = set()
            for i in range(4):
                wf = tracker.start()
                out = finish_like_server(
                    tracker, wf, lambda: pool.check([inp(i)], wf=wf)
                )
                assert out is not None
                assert wf.shard in (0, 1)
                seen.add(wf.shard)
                assert STAGE_QUEUE_WAIT in stage_names(wf)
            assert seen == {0, 1}  # round robin hit both lanes
        finally:
            pool.close()


class TestPressure:
    def make_monitor(self):
        clock = {"t": 0.0}
        mon = PressureMonitor(clock=lambda: clock["t"])
        mon.configure(enabled=True, window_s=10.0)
        return mon, clock

    def test_queue_backlog_raises_score_before_expiry(self):
        mon, clock = self.make_monitor()
        load = {"v": 0}
        mon.bind(queue=lambda: (load["v"], 100))
        snap = mon.sample()
        assert snap["score"] == 0.0
        # backlog builds: queue load climbs toward capacity over the window
        for i, v in enumerate((50, 80, 95, 98)):
            clock["t"] += 1.0
            load["v"] = v
            snap = mon.sample()
        assert snap["components"]["queue"] >= 0.9
        assert snap["score"] >= 0.9

    def test_high_water_crossing_records_flight_event(self):
        mon, clock = self.make_monitor()
        full = {"v": 0}
        mon.bind(inflight=lambda: (full["v"], 4))
        rec = flight.recorder()
        rec.clear()
        full["v"] = 4
        for _ in range(3):  # crossing records ONE event, not one per tick
            clock["t"] += 1.0
            mon.sample()
        events = [e for e in rec.dump()["events"] if e["kind"] == "pressure_high"]
        assert len(events) == 1
        assert events[0]["score"] >= HIGH_WATER
        # falling below re-arms the edge
        full["v"] = 0
        for _ in range(12):
            clock["t"] += 1.0
            mon.sample()
        full["v"] = 4
        clock["t"] += 1.0
        mon.sample()
        events = [e for e in rec.dump()["events"] if e["kind"] == "pressure_high"]
        assert len(events) == 2
        rec.clear()

    def test_fallback_fraction_is_windowed(self):
        mon, clock = self.make_monitor()
        counts = {"fb": 0.0, "dec": 0.0}
        mon.bind(fallbacks=lambda: counts["fb"], decisions=lambda: counts["dec"])
        mon.sample()
        # 100 decisions, 40 fallbacks inside the window
        clock["t"] += 1.0
        counts.update(fb=40.0, dec=100.0)
        snap = mon.sample()
        assert snap["components"]["fallback"] == pytest.approx(0.4)
        # window slides past the burst: the fraction decays to 0
        counts.update(fb=40.0, dec=200.0)
        for _ in range(12):
            clock["t"] += 1.0
            snap = mon.sample()
        assert snap["components"]["fallback"] == pytest.approx(0.0)

    def test_breaker_and_parity_map_to_degraded(self):
        mon, _clock = self.make_monitor()
        state = {"s": "closed", "shards": []}
        mon.bind(breaker=lambda: state["s"], parity=lambda: state["shards"])
        assert mon.sample()["components"]["degraded"] == 0.0
        state["s"] = "half_open"
        assert mon.sample()["components"]["degraded"] == 0.5
        state["s"] = "open"
        assert mon.sample()["components"]["degraded"] == 1.0
        state.update(s="closed", shards=[2])
        assert mon.sample()["components"]["degraded"] == 1.0

    def test_dead_sources_read_as_zero(self):
        mon, _clock = self.make_monitor()

        def boom():
            raise RuntimeError("dead source")

        mon.bind(queue=boom, inflight=boom, fallbacks=boom, breaker=boom)
        snap = mon.sample()
        assert snap["score"] == 0.0

    def test_compile_storm_inside_window(self):
        mon, clock = self.make_monitor()
        storms = {"v": 3.0}
        mon.bind(storms=lambda: storms["v"])
        assert mon.sample()["components"]["compile"] == 0.0
        clock["t"] += 1.0
        storms["v"] = 4.0  # a storm fired since the window opened
        assert mon.sample()["components"]["compile"] == 1.0
