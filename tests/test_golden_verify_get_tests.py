"""Reference test_suite_run_get_tests corpus: matrix expansion.

Mirrors internal/verify/run_test_suite_test.go Test_testSuiteRun_getTests:
a fixed fixture set, one test table per case, comparing the expanded test
list (or the exact error string) against the corpus.
"""

import os

import pytest
import yaml

from cerbos_tpu.verify.results import TestFixture, VerifyError, _SuiteRun

CORPUS = os.path.join(
    os.path.dirname(__file__), "golden", "verify", "test_suite_run_get_tests"
)

CASES = sorted(f for f in os.listdir(CORPUS) if f.endswith(".yaml"))

# run_test_suite_test.go:20-46
FIXTURE = TestFixture(
    principals={
        "employee": {"id": "employee", "roles": ["user"]},
        "manager": {"id": "manager", "roles": ["user"]},
        "department_head": {"id": "department_head", "roles": ["user"]},
    },
    principal_groups={"management": ["manager", "department_head"]},
    resources={
        "employee_leave_request": {"kind": "leave_request", "id": "employee"},
        "manager_leave_request": {"kind": "leave_request", "id": "manager"},
        "department_head_leave_request": {"kind": "leave_request", "id": "department_head"},
    },
    resource_groups={
        "management_leave_requests": ["manager_leave_request", "department_head_leave_request"]
    },
    aux_data={"test_aux_data": {"jwt": {"answer": 42}}},
)


def _test_to_dict(t, table: dict) -> dict:
    out: dict = {"name": t.name}
    if table.get("description"):
        out["description"] = table["description"]
    if t.skip:
        out["skip"] = True
    if t.skip_reason:
        out["skipReason"] = t.skip_reason
    inp: dict = {}
    if t.principal:
        inp["principal"] = t.principal
    if t.resource:
        inp["resource"] = t.resource
    if t.actions:
        inp["actions"] = t.actions
    if t.aux_data is not None:
        inp["auxData"] = t.aux_data
    out["input"] = inp
    if t.expected:
        out["expected"] = t.expected
    if t.expected_outputs:
        out["expectedOutputs"] = {
            action: {"entries": entries} for action, entries in t.expected_outputs.items()
        }
    if t.options:
        out["options"] = t.options
    return out


def _norm(v):
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


@pytest.mark.parametrize("case", CASES)
def test_get_tests(case):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = yaml.safe_load(f)

    table = tc["table"]
    run = _SuiteRun({"tests": [table]}, FIXTURE)

    want_err = (tc.get("wantErr") or "").strip()
    if want_err:
        with pytest.raises(VerifyError) as exc:
            run.get_tests()
        assert str(exc.value) == want_err, case
        return

    tests = run.get_tests()
    want = tc.get("wantTests") or []
    have = [_test_to_dict(t, table) for t in tests]
    assert _norm(want) == _norm(have), case
