"""Replay of the reference's server wire corpus (VERDICT r4 item 2 / missing #2).

`tests/golden/server/**` is `/root/reference/internal/test/testdata/server/*`
ported verbatim (request/response pairs the reference replays over real gRPC
and HTTP+JSON — internal/server/server_test.go + tests.go). This suite boots
the repo's REAL server (HTTP + gRPC listeners) against the ported golden
store fixture and replays every case, comparing responses proto-semantically
with the reference's own normalization rules (tests.go compareProto):
sorted effectiveDerivedRoles / outputs / validationErrors, cerbos_call_id
ignored-but-required, playground error-details context ignored.

Template constructs in the corpus ({{ fileString `..` | b64enc }} and
{{- readPolicy ".." | toPolicyJSON }}) mirror internal/test/template.go.

Known divergences are listed in tests/golden/UNSUPPORTED.md.
"""

import base64
import json
import pathlib
import re
import urllib.error
import urllib.request

import grpc
import pytest
import yaml
from google.protobuf import json_format

from cerbos_tpu.api.cerbos.request.v1 import request_pb2
from cerbos_tpu.api.cerbos.response.v1 import response_pb2
from cerbos_tpu.api.cerbos.policy.v1 import policy_pb2
from cerbos_tpu.bootstrap import initialize
from cerbos_tpu.config import Config
from cerbos_tpu.server.admin import AdminService
from cerbos_tpu.server.authzen import AuthZenService
from cerbos_tpu.server.playground import PlaygroundService
from cerbos_tpu.server.server import Server, ServerConfig

GOLDEN = pathlib.Path(__file__).parent / "golden"
SERVER_DIR = GOLDEN / "server"

_FILESTRING_RE = re.compile(r"{{\s*fileString\s+`([^`]+)`\s*\|\s*b64enc\s*}}")
_READPOLICY_RE = re.compile(r'{{-?\s*readPolicy\s+"([^"]+)"\s*\|\s*toPolicyJSON\s*-?}}')


def _render_template(text: str) -> str:
    """The two template constructs the corpus uses (internal/test/template.go:
    sprig b64enc over fileString, and readPolicy|toPolicyJSON)."""

    def file_b64(m: re.Match) -> str:
        data = (GOLDEN / m.group(1)).read_bytes()
        return base64.b64encode(data).decode()

    def policy_json(m: re.Match) -> str:
        raw = yaml.safe_load((GOLDEN / m.group(1)).read_text())
        pol = json_format.ParseDict(raw, policy_pb2.Policy(), ignore_unknown_fields=True)
        return json_format.MessageToJson(pol, indent=None)

    text = _FILESTRING_RE.sub(file_b64, text)
    text = _READPOLICY_RE.sub(policy_json, text)
    return text


def load_cases(*dirs: str) -> list[tuple[str, dict]]:
    cases = []
    for d in dirs:
        root = SERVER_DIR / d
        for f in sorted(root.rglob("*.yaml")):
            doc = yaml.safe_load(_render_template(f.read_text()))
            if isinstance(doc, dict):
                cases.append((str(f.relative_to(SERVER_DIR)), doc))
    return cases


# -- response normalization (tests.go compareProto) -------------------------

_SORT_LISTS = {"effectiveDerivedRoles"}


def _sort_key(v):
    return json.dumps(v, sort_keys=True)


def normalize(obj, *, drop_call_id=True):
    """Canonicalize a protojson-shaped response dict for comparison:
    - drop cerbosCallId (asserted non-empty separately)
    - sort effectiveDerivedRoles everywhere
    - sort outputs entries by (src, action)
    - sort validationErrors by content
    - sort playground failure errors by content; drop their error context
    - drop authzen response 'context'
    """
    if isinstance(obj, list):
        return [normalize(x, drop_call_id=drop_call_id) for x in obj]
    if not isinstance(obj, dict):
        return obj
    out = {}
    for k, v in obj.items():
        if drop_call_id and k == "cerbosCallId":
            continue
        if k in _SORT_LISTS and isinstance(v, list):
            out[k] = sorted(v)
            continue
        if k in ("outputs", "validationErrors", "errors") and isinstance(v, list):
            out[k] = sorted(
                (normalize(x, drop_call_id=drop_call_id) for x in v), key=_sort_key
            )
            continue
        out[k] = normalize(v, drop_call_id=drop_call_id)
    return out


def canon(resp_cls, payload: dict) -> dict:
    """protojson dict → proto → canonical dict (field presence, enum names
    and defaults normalized exactly the way protojson would emit them)."""
    msg = json_format.ParseDict(payload, resp_cls(), ignore_unknown_fields=False)
    return json_format.MessageToDict(msg)


# -- server fixtures ---------------------------------------------------------


def _mk_server(tmp_path, storage_overrides: list[str]):
    config = Config.load(
        overrides=[
            *storage_overrides,
            "server.httpListenAddr=127.0.0.1:0",
            "server.grpcListenAddr=127.0.0.1:0",
            "server.adminAPI.enabled=true",
            # the reference's wire-corpus server runs with lowered limits
            # (server_test.go:386-388) so the "too many" cases trip
            "server.requestLimits.maxActionsPerResource=5",
            "server.requestLimits.maxResourcesPerRequest=5",
            "schema.enforcement=reject",
            f"auxData.jwt.keySets=[{{\"id\": \"cerbos\", \"local\": {{\"file\": \"{GOLDEN}/auxdata/keys/verify_key.jwk\"}}}}]",
            "engine.tpu.enabled=false",
        ]
    )
    core = initialize(config, use_tpu=False)
    admin = AdminService(core, username="cerbos", password="cerbosAdmin")
    srv = Server(
        core.service,
        ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
        admin_service=admin,
        extra_services=[AuthZenService(core.service), PlaygroundService()],
    )
    srv.start()
    return core, srv


@pytest.fixture(scope="module")
def disk_server():
    core, srv = _mk_server(None, [f"storage.disk.directory={GOLDEN / 'store'}"])
    yield srv
    srv.stop()
    core.close()


@pytest.fixture(scope="module")
def sqlite_server(tmp_path_factory):
    db = tmp_path_factory.mktemp("db") / "cerbos.sqlite"
    core, srv = _mk_server(
        None,
        ["storage.driver=sqlite3", f"storage.sqlite3.dsn={db}"],
    )
    yield srv
    srv.stop()
    core.close()


# -- call-kind registry ------------------------------------------------------

# kind -> (http path, grpc method, request class, response class)
KINDS = {
    "checkResources": (
        "/api/check/resources",
        "/cerbos.svc.v1.CerbosService/CheckResources",
        request_pb2.CheckResourcesRequest,
        response_pb2.CheckResourcesResponse,
    ),
    "checkResourceSet": (
        "/api/check",
        "/cerbos.svc.v1.CerbosService/CheckResourceSet",
        request_pb2.CheckResourceSetRequest,
        response_pb2.CheckResourceSetResponse,
    ),
    "checkResourceBatch": (
        "/api/check_resource_batch",
        "/cerbos.svc.v1.CerbosService/CheckResourceBatch",
        request_pb2.CheckResourceBatchRequest,
        response_pb2.CheckResourceBatchResponse,
    ),
    "planResources": (
        "/api/plan/resources",
        "/cerbos.svc.v1.CerbosService/PlanResources",
        request_pb2.PlanResourcesRequest,
        response_pb2.PlanResourcesResponse,
    ),
}


def http_post_raw(server, path, body, auth=None):
    headers = {"Content-Type": "application/json"}
    if auth:
        tok = base64.b64encode(f"{auth[0]}:{auth[1]}".encode()).decode()
        headers["Authorization"] = f"Basic {tok}"
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.http_port}{path}",
        data=json.dumps(body).encode(),
        headers=headers,
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except Exception:  # noqa: BLE001
            return e.code, {}


def _case_kind(doc: dict):
    for k in doc:
        if k not in ("description", "name", "wantStatus", "wantError"):
            return k
    return None


def replay_http(server, doc: dict, name: str, auth=None):
    kind = _case_kind(doc)
    call = doc[kind]
    want_status = (doc.get("wantStatus") or {}).get("httpStatusCode", 200)
    if kind in KINDS:
        path, _, _, resp_cls = KINDS[kind]
    elif kind == "accessEvaluation":
        path, resp_cls = "/access/v1/evaluation", None
    elif kind == "accessEvaluationBatch":
        path, resp_cls = "/access/v1/evaluations", None
    elif kind == "playgroundValidate":
        path, resp_cls = "/api/playground/validate", None
    elif kind == "playgroundEvaluate":
        path, resp_cls = "/api/playground/evaluate", None
    elif kind == "playgroundTest":
        path, resp_cls = "/api/playground/test", None
    elif kind == "playgroundProxy":
        path, resp_cls = "/api/playground/proxy", None
    elif kind == "adminAddOrUpdatePolicy":
        path, resp_cls = "/admin/policy", None
    elif kind == "adminAddOrUpdateSchema":
        path, resp_cls = "/admin/schema", None
    else:
        pytest.fail(f"{name}: unknown call kind {kind}")
    status, have = http_post_raw(server, path, call["input"], auth=auth)
    assert status == want_status, f"{name}: HTTP {status} != {want_status}: {have}"
    if doc.get("wantError") or want_status != 200:
        return
    want = call.get("wantResponse", {})
    if resp_cls is not None:
        want_n = normalize(canon(resp_cls, want))
        have_n = normalize(canon(resp_cls, have))
    else:
        want_n = normalize(want)
        have_n = normalize(have)
    assert have_n == want_n, (
        f"{name}: response mismatch\nwant: {json.dumps(want_n, indent=2, sort_keys=True)}\n"
        f"have: {json.dumps(have_n, indent=2, sort_keys=True)}"
    )


def replay_grpc(server, doc: dict, name: str, auth=None):
    kind = _case_kind(doc)
    if kind not in KINDS:
        pytest.skip(f"{kind} not exposed over gRPC in this build")
    call = doc[kind]
    want_code = (doc.get("wantStatus") or {}).get("grpcStatusCode", 0)
    _, method, req_cls, resp_cls = KINDS[kind]
    req = json_format.ParseDict(call["input"], req_cls(), ignore_unknown_fields=True)
    channel = grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}")
    try:
        stub = channel.unary_unary(
            method,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=resp_cls.FromString,
        )
        metadata = []
        if auth:
            tok = base64.b64encode(f"{auth[0]}:{auth[1]}".encode()).decode()
            metadata.append(("authorization", f"Basic {tok}"))
        try:
            resp = stub(req, timeout=30, metadata=metadata or None)
            code = 0
        except grpc.RpcError as e:
            code = e.code().value[0]
            resp = None
        assert code == want_code, f"{name}: gRPC code {code} != {want_code}"
        if doc.get("wantError") or want_code != 0:
            return
        want = call.get("wantResponse", {})
        want_n = normalize(canon(resp_cls, want))
        have_n = normalize(json_format.MessageToDict(resp))
        assert have_n == want_n, (
            f"{name}: gRPC response mismatch\n"
            f"want: {json.dumps(want_n, indent=2, sort_keys=True)}\n"
            f"have: {json.dumps(have_n, indent=2, sort_keys=True)}"
        )
    finally:
        channel.close()


CHECK_CASES = load_cases("checks", "plan_resources")


@pytest.mark.parametrize("name,doc", CHECK_CASES, ids=[c[0] for c in CHECK_CASES])
def test_http_checks(disk_server, name, doc):
    replay_http(disk_server, doc, name)


@pytest.mark.parametrize("name,doc", CHECK_CASES, ids=[c[0] for c in CHECK_CASES])
def test_grpc_checks(disk_server, name, doc):
    replay_grpc(disk_server, doc, name)
