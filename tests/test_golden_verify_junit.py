"""Reference verify_junit corpus: JUnit XML byte-parity.

Mirrors internal/verify/junit/junit_test.go TestJUnit: run the policy tests
from the txtar archive against the golden store engine, build JUnit XML
(verbose), and compare the marshalled string to the golden byte-for-byte.
"""

import os

import pytest

from cerbos_tpu.verify.junit import build
from cerbos_tpu.verify.results import Config, verify
from test_golden_verify import expand_txtar

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "verify_junit", "cases")

CASES = sorted(
    f for f in os.listdir(CORPUS)
    if f.endswith(".yaml") and os.path.exists(os.path.join(CORPUS, f + ".golden"))
)


@pytest.fixture(scope="module")
def engine():
    # the junit harness uses its own store (verify_junit/store — mkEngine in
    # internal/verify/junit/junit_test.go:124-127), not the main test store
    from cerbos_tpu.compile import compile_policy_set
    from cerbos_tpu.engine.engine import Engine
    from cerbos_tpu.storage.disk import DiskStore

    store = DiskStore(os.path.join(os.path.dirname(CORPUS), "store"))
    return Engine.from_policies(compile_policy_set(store.get_all()))


@pytest.mark.parametrize("case", CASES)
def test_junit_case(case, engine, tmp_path):
    with open(os.path.join(CORPUS, case + ".input"), encoding="utf-8") as f:
        expand_txtar(f.read(), str(tmp_path))
    with open(os.path.join(CORPUS, case + ".golden"), encoding="utf-8") as f:
        want = f.read()

    results = verify(str(tmp_path), engine, Config())
    have = build(results, verbose=True)
    assert want == have, case
