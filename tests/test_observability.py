"""Observability layer: W3C trace-context propagation, span export, torn-read
safety of the metrics registry, and the OTLP span/metrics exporters against an
in-process HTTP capture server.
"""

import http.server
import json
import re
import threading
import time

import pytest

from cerbos_tpu import observability as obs


class _Capture(obs.SpanExporter):
    def __init__(self):
        self.spans = []

    def export(self, span, duration_ms):
        self.spans.append((span, duration_ms))


class _exporter_swap:
    """Temporarily install an exporter; always restores the previous one."""

    def __init__(self, exporter):
        self.exporter = exporter

    def __enter__(self):
        self._old = obs._exporter
        obs.set_exporter(self.exporter)
        return self.exporter

    def __exit__(self, *exc):
        obs.set_exporter(self._old)


class TestTraceparent:
    def test_roundtrip(self):
        ctx = obs.SpanContext(obs.new_trace_id(), obs.new_span_id())
        assert obs.parse_traceparent(ctx.to_traceparent()) == ctx

    def test_format(self):
        ctx = obs.SpanContext("a" * 32, "b" * 16)
        assert ctx.to_traceparent() == f"00-{'a' * 32}-{'b' * 16}-01"
        assert obs.SpanContext("a" * 32, "b" * 16, sampled=False).to_traceparent().endswith("-00")

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-short-id-01",
            f"ff-{'a' * 32}-{'b' * 16}-01",  # version ff is forbidden
            f"00-{'0' * 32}-{'b' * 16}-01",  # all-zero trace id
            f"00-{'a' * 32}-{'0' * 16}-01",  # all-zero span id
            f"00-{'A' * 31}Z-{'b' * 16}-01",  # non-hex
        ],
    )
    def test_malformed_rejected(self, header):
        assert obs.parse_traceparent(header) is None

    def test_case_and_whitespace_tolerated(self):
        got = obs.parse_traceparent(f"  00-{'A' * 32}-{'B' * 16}-01  ")
        assert got == obs.SpanContext("a" * 32, "b" * 16)

    def test_unsampled_flag(self):
        got = obs.parse_traceparent(f"00-{'a' * 32}-{'b' * 16}-00")
        assert got is not None and got.sampled is False


class TestIds:
    def test_proper_w3c_lengths(self):
        """Ids are generated full-width, never zero-padded at export time."""
        for _ in range(16):
            assert re.fullmatch(r"[0-9a-f]{32}", obs.new_trace_id())
            assert re.fullmatch(r"[0-9a-f]{16}", obs.new_span_id())
        span = obs.Span(name="x", trace_id=obs.new_trace_id())
        assert len(span.span_id) == 16


class TestSpanParenting:
    def test_parent_override_crosses_threads(self):
        cap = _Capture()
        with _exporter_swap(cap):
            with obs.start_span("request") as req:
                ctx = req.context
            done = threading.Event()

            def other_thread():
                with obs.start_span("remote.child", parent=ctx):
                    pass
                done.set()

            threading.Thread(target=other_thread).start()
            assert done.wait(5)
        child = next(s for s, _ in cap.spans if s.name == "remote.child")
        assert child.trace_id == req.trace_id
        assert child.parent_id == req.span_id

    def test_links_attach(self):
        cap = _Capture()
        others = [obs.SpanContext(obs.new_trace_id(), obs.new_span_id()) for _ in range(3)]
        with _exporter_swap(cap):
            with obs.start_span("batch", links=others):
                pass
        span = cap.spans[0][0]
        assert span.links == others

    def test_thread_local_nesting_restored(self):
        cap = _Capture()
        with _exporter_swap(cap):
            with obs.start_span("outer") as outer:
                with obs.start_span("inner", parent=obs.SpanContext("c" * 32, "d" * 16)):
                    pass
                # the explicit-parent span must not leak as current
                assert obs.current_span_context() == outer.context

    def test_export_span_synthesizes_interval(self):
        cap = _Capture()
        parent = obs.SpanContext(obs.new_trace_id(), obs.new_span_id())
        t0 = time.time_ns()
        with _exporter_swap(cap):
            obs.export_span("batch.device", parent, t0, 0.25, batch_id=7)
        span, duration_ms = cap.spans[0]
        assert span.trace_id == parent.trace_id and span.parent_id == parent.span_id
        assert span.start_wall_ns == t0
        assert duration_ms == pytest.approx(250.0)


class TestTornReads:
    def test_histogram_render_is_consistent_under_writes(self):
        """A render racing observe() must never expose cumulative buckets
        that don't sum to _count (the torn read the lock snapshot fixes)."""
        h = obs.Histogram("t_torn_hist", "x", buckets=[0.1, 1.0, 10.0])
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                h.observe((i % 3) * 0.09 + 0.01)
                i += 1

        threads = [threading.Thread(target=writer, daemon=True) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for _ in range(200):
                counts, total, count = h.snapshot()
                assert sum(counts) == count
                lines = h.render()
                inf = int(lines[-3].rsplit(" ", 1)[1])
                n = int(lines[-1].rsplit(" ", 1)[1])
                assert inf == n, lines  # +Inf cumulative == count
        finally:
            stop.set()
            for t in threads:
                t.join(2)

    def test_gauge_render_snapshot(self):
        g = obs.Gauge("t_torn_gauge", "x", track_max=True)
        g.set(3)
        lines = g.render()
        assert lines[1].endswith(" 3") and lines[3].endswith(" 3")

    def test_percentile_interpolation(self):
        h = obs.Histogram("t_pct", "x", buckets=[1.0, 2.0, 4.0])
        for v in (0.5, 1.5, 1.5, 3.0):
            h.observe(v)
        assert 0 < h.percentile(0.5) <= 2.0
        assert h.percentile(0.99) <= 4.0
        assert obs.Histogram("t_pct_empty", "x").percentile(0.5) == 0.0


class TestHistogramVec:
    def test_renders_per_label_series(self):
        vec = obs.HistogramVec("t_stage_seconds", "stage latency", label="stage", buckets=[0.1, 1.0])
        vec.observe("pack", 0.05)
        vec.observe("device", 0.5)
        text = "\n".join(vec.render())
        assert '# TYPE t_stage_seconds histogram' in text
        assert 't_stage_seconds_bucket{stage="pack",le="0.1"} 1' in text
        assert 't_stage_seconds_bucket{stage="device",le="+Inf"} 1' in text
        assert 't_stage_seconds_count{stage="pack"} 1' in text

    def test_series_per_label(self):
        vec = obs.HistogramVec("t_sv", "x", label="stage")
        vec.observe("pack", 0.5)
        s = vec.series()
        assert s["t_sv_pack_count"] == 1.0


class TestRegistryTypes:
    def test_conflicting_instrument_type_raises(self):
        reg = obs.MetricsRegistry()
        reg.counter("t_conflict_total", "x")
        with pytest.raises(TypeError):
            reg.gauge("t_conflict_total", "x")
        with pytest.raises(TypeError):
            reg.histogram("t_conflict_total", "x")
        reg.gauge("t_conflict_gauge", "x")
        with pytest.raises(TypeError):
            reg.counter_vec("t_conflict_gauge", "x")

    def test_counter_upgrade_to_vec_preserves_total(self):
        reg = obs.MetricsRegistry()
        c = reg.counter("t_up_total", "x")
        c.inc(3)
        vec = reg.counter_vec("t_up_total", "x", label="reason")
        assert vec.value == 3.0
        # existing readers holding counter() still see the summed total
        assert reg.counter("t_up_total").value == 3.0

    def test_instruments_walk(self):
        reg = obs.MetricsRegistry()
        reg.counter("t_walk_a_total", "a")
        reg.histogram_vec("t_walk_b_seconds", "b")
        inst = reg.instruments()
        assert set(inst) == {"t_walk_a_total", "t_walk_b_seconds"}


class _Sink(http.server.BaseHTTPRequestHandler):
    received = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        type(self).received.append((self.path, json.loads(body)))
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"{}")

    def log_message(self, *a):  # noqa: D102
        pass


@pytest.fixture()
def sink():
    _Sink.received = []
    srv = http.server.HTTPServer(("127.0.0.1", 0), _Sink)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv
    finally:
        srv.shutdown()


class TestOTLPSpanExporter:
    def test_payload_shape_ids_timestamps_links(self, sink):
        exp = obs.OTLPSpanExporter(
            f"http://127.0.0.1:{sink.server_port}", service_name="t-svc", flush_interval_s=60
        )
        link = obs.SpanContext(obs.new_trace_id(), obs.new_span_id())
        span = obs.Span(name="batch.submit", trace_id=obs.new_trace_id(), links=[link])
        span.set_attribute("batch_id", 3)
        wall = span.start_wall_ns
        exp.export(span, 12.0)
        exp.close()
        assert _Sink.received
        path, body = _Sink.received[0]
        assert path == "/v1/traces"
        res = body["resourceSpans"][0]
        attrs = {a["key"]: a["value"]["stringValue"] for a in res["resource"]["attributes"]}
        assert attrs["service.name"] == "t-svc"
        s = res["scopeSpans"][0]["spans"][0]
        # ids export verbatim as full-width W3C hex — no zero padding
        assert s["traceId"] == span.trace_id and len(s["traceId"]) == 32
        assert s["spanId"] == span.span_id and len(s["spanId"]) == 16
        # timestamps anchor on the span's wall-clock START, not flush time
        assert int(s["startTimeUnixNano"]) == wall
        assert int(s["endTimeUnixNano"]) == wall + 12_000_000
        assert s["links"] == [{"traceId": link.trace_id, "spanId": link.span_id}]
        assert {"key": "batch_id", "value": {"stringValue": "3"}} in s["attributes"]

    def test_batching_splits_at_max_batch(self, sink):
        exp = obs.OTLPSpanExporter(
            f"http://127.0.0.1:{sink.server_port}", flush_interval_s=60, max_batch=4
        )
        for i in range(10):
            exp.export(obs.Span(name=f"s{i}", trace_id=obs.new_trace_id()), 1.0)
        exp.close()
        sizes = [len(b["resourceSpans"][0]["scopeSpans"][0]["spans"]) for _, b in _Sink.received]
        assert sum(sizes) == 10
        assert max(sizes) <= 4

    def test_bounded_buffer_drops_oldest(self):
        # endpoint points nowhere; nothing ever flushes, so the buffer bounds
        exp = obs.OTLPSpanExporter("http://127.0.0.1:1", flush_interval_s=3600, max_batch=2)
        try:
            for i in range(50):
                exp.export(obs.Span(name=f"s{i}", trace_id="a" * 32), 1.0)
            with exp._lock:
                names = [s["name"] for s in exp._buf]
            assert len(names) <= exp.max_batch * 4
            assert names[-1] == "s49"  # newest kept; oldest dropped
        finally:
            exp._stop.set()

    def test_collector_down_drops_without_blocking(self):
        exp = obs.OTLPSpanExporter("http://127.0.0.1:1", flush_interval_s=3600)
        try:
            exp.export(obs.Span(name="x", trace_id="a" * 32), 1.0)
            t0 = time.perf_counter()
            exp.flush()  # connection refused: drop, don't block or raise
            assert time.perf_counter() - t0 < 5.0
            with exp._lock:
                assert exp._buf == []
        finally:
            exp._stop.set()


class TestOTLPMetricsExporter:
    def test_payload_shape(self, sink):
        exp = obs.OTLPMetricsExporter(
            f"http://127.0.0.1:{sink.server_port}", service_name="t-svc", interval_s=3600
        )
        exp.add_source(lambda: {"cerbos_tpu_test_gauge": 4.5})
        exp.close()
        assert _Sink.received
        path, body = _Sink.received[0]
        assert path == "/v1/metrics"
        m = body["resourceMetrics"][0]["scopeMetrics"][0]["metrics"][0]
        assert m["name"] == "cerbos_tpu_test_gauge"
        assert m["gauge"]["dataPoints"][0]["asDouble"] == 4.5

    def test_collector_down_drops(self):
        exp = obs.OTLPMetricsExporter("http://127.0.0.1:1", interval_s=3600)
        exp.add_source(lambda: {"x": 1.0})
        t0 = time.perf_counter()
        exp.close()  # flush against a dead collector must not raise or hang
        assert time.perf_counter() - t0 < 5.0

    def test_broken_source_skipped(self, sink):
        exp = obs.OTLPMetricsExporter(f"http://127.0.0.1:{sink.server_port}", interval_s=3600)
        exp.add_source(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        exp.add_source(lambda: {"ok_metric": 1.0})
        exp.close()
        names = {
            m["name"]
            for _, b in _Sink.received
            for m in b["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
        }
        assert names == {"ok_metric"}
