"""Differential tests for the two-level packed bitmap rule index.

The bitmap backend (ruletable/index.py) must return byte-identical row lists
to the legacy set-algebra oracle for every query, across arbitrary
build/delete interleavings and for both sweep kernels (native C and numpy
fallback). Plus memo-cold regressions: with the request-shape memos disabled,
queries over the bench corpus shapes must stay correct and the two backends
must agree.
"""

import random

import pytest

import cerbos_tpu.ruletable.index as index_mod
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.ruletable.index import Index, PackedBitmap, _sweep_numpy
from cerbos_tpu.ruletable.rows import KIND_PRINCIPAL, KIND_RESOURCE, RuleRow
from cerbos_tpu.util import bench_corpus


def row_key(r: RuleRow):
    """Identity of a query result row, synthetic DENYs included."""
    return (
        r.id,
        r.origin_fqn,
        r.scope,
        r.version,
        r.policy_kind,
        r.resource,
        r.role,
        r.action,
        r.effect,
        r.from_role_policy,
        r.no_match_for_scope_permissions,
    )


def assert_identical(bitmap_idx: Index, legacy_idx: Index, query: tuple):
    got = [row_key(r) for r in bitmap_idx.query(*query)]
    want = [row_key(r) for r in legacy_idx.query(*query)]
    assert got == want, f"divergence for query {query!r}"


# -- PackedBitmap unit tests --------------------------------------------------


class TestPackedBitmap:
    def test_add_discard_both_levels(self):
        bm = PackedBitmap()
        for rid in (0, 63, 64, 4095, 4096, 70000):
            bm.add(rid)
        assert bm.n == 6
        # summary bit j set iff words[j] != 0
        for w, word in enumerate(bm.words):
            have = bool(int(bm.summary[w >> 6]) & (1 << (w & 63)))
            assert have == (int(word) != 0)
        bm.discard(64)
        bm.discard(64)  # idempotent
        bm.discard(10**6)  # out of range: no-op
        assert bm.n == 5
        # word 1 is now empty: its summary bit must be cleared (free-id reuse
        # correctness depends on this)
        assert not int(bm.summary[0]) & (1 << 1)
        _, ids = _sweep_numpy([bm.words], [bm.summary], None, None)
        assert ids == [0, 63, 4095, 4096, 70000]

    def test_add_existing_is_noop(self):
        bm = PackedBitmap()
        bm.add(7)
        bm.add(7)
        assert bm.n == 1

    def test_union(self):
        a, b = PackedBitmap(), PackedBitmap()
        for rid in (1, 100, 5000):
            a.add(rid)
        for rid in (100, 200):
            b.add(rid)
        u = PackedBitmap.union([a, b])
        assert u.n == 4
        _, ids = _sweep_numpy([u.words], [u.summary], None, None)
        assert ids == [1, 100, 200, 5000]
        assert PackedBitmap.union([]).n == 0


# -- sweep kernel equivalence -------------------------------------------------


@pytest.mark.index_parity
def test_native_and_numpy_kernels_agree():
    if not index_mod._native_resolved:
        index_mod._resolve_native()
    nat = index_mod._native_bitmap_sweep
    if nat is None:
        pytest.skip("native extension unavailable")
    rng = random.Random(7)
    for trial in range(50):
        nbits = rng.choice([64, 640, 8192])
        dims = []
        for _ in range(rng.randint(1, 5)):
            bm = PackedBitmap()
            for _ in range(rng.randint(0, 200)):
                bm.add(rng.randrange(nbits))
            # ensure arrays exist even for an empty bitmap
            bm.add(0)
            bm.discard(0)
            dims.append(bm)
        extra = None
        if rng.random() < 0.5:
            ebm = PackedBitmap()
            for _ in range(rng.randint(0, 50)):
                ebm.add(rng.randrange(nbits))
            extra = ebm.words
        ws = [d.words for d in dims]
        ss = [d.summary for d in dims]
        want = _sweep_numpy(ws, ss, extra.copy() if extra is not None else None, None)
        have_sum = nat(ws, ss, extra, None)
        have_lin = nat(ws, None, extra, None)
        assert have_sum == want, f"trial {trial}: summary sweep diverged"
        assert have_lin == want, f"trial {trial}: linear sweep diverged"


# -- seeded fuzz: random build/delete/query interleavings ---------------------


SCOPES = ["", "acme", "acme.hr", "acme.hr.uk"]
VERSIONS = ["default", "v1"]
RESOURCES = ["leave_request", "purchase_order", "expense:claim", "salary_record"]
RESOURCE_PATTERNS = RESOURCES + ["*", "expense:*", "leave_*"]
ROLES = ["employee", "manager", "admin", "auditor"]
ACTIONS = ["view", "view:public", "approve", "delete", "create"]
ACTION_PATTERNS = ACTIONS + ["*", "view:*"]


def random_row(rng: random.Random, fqn: str) -> RuleRow:
    kind = rng.choice([KIND_PRINCIPAL, KIND_RESOURCE])
    role_policy = kind == KIND_RESOURCE and rng.random() < 0.15
    return RuleRow(
        origin_fqn=fqn,
        scope=rng.choice(SCOPES),
        version=rng.choice(VERSIONS),
        policy_kind=kind,
        resource=rng.choice(RESOURCE_PATTERNS),
        role=rng.choice(ROLES) if rng.random() < 0.8 else "*",
        action=None if role_policy else rng.choice(ACTION_PATTERNS),
        allow_actions=(
            frozenset(rng.sample(ACTION_PATTERNS, rng.randint(1, 3)))
            if role_policy
            else None
        ),
        principal=rng.choice(["", "", "", "alice", "bob"]) or None,
        effect=rng.choice(["EFFECT_ALLOW", "EFFECT_DENY"]),
    )


def random_query(rng: random.Random) -> tuple:
    return (
        rng.choice(VERSIONS + [""]),
        rng.choice(RESOURCES + [""]),
        rng.choice(SCOPES + ["nonexistent"]),
        rng.choice(ACTIONS + [""]),
        rng.sample(ROLES, rng.randint(0, 3)),
        rng.choice([KIND_PRINCIPAL, KIND_RESOURCE, ""]),
        rng.choice(["", "alice", "bob", "charlie"]),
    )


@pytest.mark.index_parity
@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("kernel", ["native", "numpy"])
def test_differential_fuzz(seed, kernel, monkeypatch):
    if not index_mod._native_resolved:
        index_mod._resolve_native()
    if kernel == "numpy":
        monkeypatch.setattr(index_mod, "_native_bitmap_sweep", None)
        monkeypatch.setattr(index_mod, "_native_bitmap_any", None)
    elif index_mod._native_bitmap_sweep is None:
        pytest.skip("native extension unavailable")

    rng = random.Random(seed)
    bitmap_idx = Index(backend="bitmap", memo_enabled=rng.random() < 0.5)
    legacy_idx = Index(backend="legacy", memo_enabled=rng.random() < 0.5)
    live_fqns: list[str] = []
    fqn_counter = 0

    for step in range(300):
        op = rng.random()
        if op < 0.35 or not live_fqns:
            # ingest a policy worth of rows; both indexes assign the same ids
            # because free-list order is mutation-order deterministic
            fqn = f"policy.{fqn_counter}"
            fqn_counter += 1
            n = rng.randint(1, 5)
            rows_a = [random_row(rng, fqn) for _ in range(n)]
            bitmap_idx.index_rules(rows_a)
            # clone the rows for the oracle: index_rules assigns ids in-place,
            # so the two indexes must not share RuleRow objects
            rows_b = [
                RuleRow(**{
                    f: getattr(r, f)
                    for f in (
                        "origin_fqn", "scope", "version", "policy_kind",
                        "resource", "role", "action", "allow_actions",
                        "principal", "effect",
                    )
                })
                for r in rows_a
            ]
            legacy_idx.index_rules(rows_b)
            live_fqns.append(fqn)
        elif op < 0.5:
            fqn = rng.choice(live_fqns)
            live_fqns.remove(fqn)
            bitmap_idx.delete_policy(fqn)
            legacy_idx.delete_policy(fqn)
        else:
            assert_identical(bitmap_idx, legacy_idx, random_query(rng))

    # final sweep: a fixed battery over the whole surviving table
    battery_rng = random.Random(seed + 1000)
    for _ in range(100):
        assert_identical(bitmap_idx, legacy_idx, random_query(battery_rng))
    assert [row_key(r) for r in bitmap_idx.get_all_rows()] == [
        row_key(r) for r in legacy_idx.get_all_rows()
    ]


# -- memo-cold regression over the bench corpus shapes ------------------------


@pytest.fixture(scope="module")
def bench_tables():
    n_mods = 20  # small slice of the bench corpus: fast but same shapes
    compiled = compile_policy_set(list(parse_policies(bench_corpus.corpus_yaml(n_mods))))
    rt_bitmap = build_rule_table(compiled, index_backend="bitmap")
    rt_legacy = build_rule_table(compiled, index_backend="legacy")
    return n_mods, rt_bitmap, rt_legacy


@pytest.mark.index_parity
def test_memo_cold_bench_corpus_parity(bench_tables):
    from bench import index_query_tuples

    n_mods, rt_bitmap, rt_legacy = bench_tables
    rt_bitmap.idx.set_memo_enabled(False)
    rt_legacy.idx.set_memo_enabled(False)
    assert not rt_bitmap.idx.memo_enabled

    qs = index_query_tuples(bench_corpus.requests(128, n_mods))
    assert qs
    nonempty = 0
    for q in qs:
        got = rt_bitmap.idx.query(*q)
        want = rt_legacy.idx.query(*q)
        assert [row_key(r) for r in got] == [row_key(r) for r in want]
        nonempty += bool(got)
        # memo really is cold: the result cache must stay empty
        assert not rt_bitmap.idx._query_cache
        assert not rt_legacy.idx._query_cache
    assert nonempty > 0, "corpus queries all came back empty — corpus broken"


@pytest.mark.index_parity
def test_memo_cold_exists_parity(bench_tables):
    n_mods, rt_bitmap, rt_legacy = bench_tables
    rt_bitmap.idx.set_memo_enabled(False)
    rt_legacy.idx.set_memo_enabled(False)
    scopes_chains = [[""], ["acme", ""], ["nonexistent"]]
    for version in ("default", "v1", ""):
        for scopes in scopes_chains:
            assert rt_bitmap.idx.scoped_principal_exists(version, scopes) == (
                rt_legacy.idx.scoped_principal_exists(version, scopes)
            )
            for res in ("leave_request", "purchase_order", "nope"):
                assert rt_bitmap.idx.scoped_resource_exists(version, res, scopes) == (
                    rt_legacy.idx.scoped_resource_exists(version, res, scopes)
                )
        assert not rt_bitmap.idx._exists_cache


def test_memo_toggle_restores_caching(bench_tables):
    n_mods, rt_bitmap, _ = bench_tables
    rt_bitmap.idx.set_memo_enabled(True)
    q = ("default", "leave_request", "", "view:public", ["employee"], KIND_RESOURCE, "")
    first = rt_bitmap.idx.query(*q)
    assert rt_bitmap.idx._query_cache
    assert rt_bitmap.idx.query(*q) is first  # memo hit returns the shared list


def test_env_backend_selection(monkeypatch):
    monkeypatch.setenv("CERBOS_TPU_RULE_INDEX", "legacy")
    assert Index().backend == "legacy"
    monkeypatch.setenv("CERBOS_TPU_RULE_INDEX", "bitmap")
    assert Index().backend == "bitmap"
    monkeypatch.setenv("CERBOS_TPU_RULE_INDEX", "bogus")
    assert Index().backend == "bitmap"  # unknown env value falls back
    with pytest.raises(ValueError):
        Index(backend="bogus")


def test_free_id_reuse_clears_both_levels():
    idx = Index(backend="bitmap")
    rows = [
        RuleRow(
            origin_fqn="p.a", scope="", version="default",
            policy_kind=KIND_RESOURCE, resource="doc", role="admin",
            action="view", effect="EFFECT_ALLOW",
        )
        for _ in range(70)  # spans more than one 64-bit word
    ]
    idx.index_rules(rows)
    q = ("default", "doc", "", "view", ["admin"], KIND_RESOURCE, "")
    assert len(idx.query(*q)) == 70
    idx.delete_policy("p.a")
    assert idx.query(*q) == []
    # every dimension bitmap must have zeroed both levels
    for dim in (idx._scope, idx._version, idx._policy_kind):
        assert not dim.bm
    assert not idx.resource.lit_bm and not idx.role.lit_bm
    # re-ingest reuses the freed ids: stale bits would corrupt these results
    idx.index_rules(rows[:3])
    assert len(idx.query(*q)) == 3
