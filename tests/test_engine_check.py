"""Check-algorithm semantics tests, modeled on the reference's golden engine
cases (internal/test/testdata/engine) but written as an independent corpus
covering the same behaviors: RBAC, ABAC conditions, derived roles, principal
policy precedence, scope hierarchies, scope permissions, role policies with
synthetic denies, wildcards, outputs, and default-deny."""


from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, Engine, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies

POLICIES = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: leave_roles
  definitions:
    - name: owner
      parentRoles: [employee]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id
    - name: direct_manager
      parentRoles: [manager]
      condition:
        match:
          expr: request.resource.attr.managerId == request.principal.id
    - name: any_employee
      parentRoles: [employee]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: leave_request
  version: default
  importDerivedRoles: [leave_roles]
  rules:
    - actions: ["view:*"]
      effect: EFFECT_ALLOW
      derivedRoles: [owner, direct_manager]
    - actions: ["create"]
      effect: EFFECT_ALLOW
      derivedRoles: [any_employee]
    - actions: ["approve"]
      effect: EFFECT_ALLOW
      derivedRoles: [direct_manager]
      condition:
        match:
          expr: request.resource.attr.status == "PENDING_APPROVAL"
      output:
        when:
          ruleActivated: '"approved by " + request.principal.id'
          conditionNotMet: '"not pending"'
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
    - actions: ["delete"]
      effect: EFFECT_DENY
      roles: [auditor]
---
apiVersion: api.cerbos.dev/v1
principalPolicy:
  principal: daffy
  version: default
  rules:
    - resource: leave_request
      actions:
        - action: "approve"
          effect: EFFECT_DENY
          name: no_approve_for_daffy
    - resource: "secret_*"
      actions:
        - action: "view"
          effect: EFFECT_ALLOW
"""


def make_engine(src=POLICIES, **kwargs):
    policies = list(parse_policies(src))
    return Engine.from_policies(compile_policy_set(policies), **kwargs)


def check_one(engine, principal, resource, actions, params=None):
    out = engine.check(
        [CheckInput(principal=principal, resource=resource, actions=actions, request_id="t")],
        params=params,
    )[0]
    return out


def P(id="john", roles=("employee",), attr=None, scope="", version=""):
    return Principal(id=id, roles=list(roles), attr=attr or {}, scope=scope, policy_version=version)


def R(kind="leave_request", id="XX1", attr=None, scope="", version=""):
    return Resource(kind=kind, id=id, attr=attr or {}, scope=scope, policy_version=version)


class TestBasicRBACAndABAC:
    def test_owner_can_view(self):
        out = check_one(make_engine(), P(), R(attr={"owner": "john"}), ["view:public"])
        assert out.actions["view:public"].effect == "EFFECT_ALLOW"
        assert out.actions["view:public"].policy == "resource.leave_request.vdefault"
        assert "owner" in out.effective_derived_roles
        assert "any_employee" in out.effective_derived_roles

    def test_non_owner_cannot_view(self):
        out = check_one(make_engine(), P(), R(attr={"owner": "sally"}), ["view:public"])
        assert out.actions["view:public"].effect == "EFFECT_DENY"

    def test_default_deny_unknown_action(self):
        out = check_one(make_engine(), P(), R(attr={"owner": "john"}), ["bogus_action"])
        assert out.actions["bogus_action"].effect == "EFFECT_DENY"

    def test_unknown_resource_kind_no_match(self):
        out = check_one(make_engine(), P(), R(kind="nonexistent"), ["view"])
        assert out.actions["view"].effect == "EFFECT_DENY"
        assert out.actions["view"].policy == "NO_MATCH"

    def test_condition_gates_allow(self):
        eng = make_engine()
        ok = check_one(eng, P(id="boss", roles=["manager"]), R(attr={"managerId": "boss", "status": "PENDING_APPROVAL"}), ["approve"])
        assert ok.actions["approve"].effect == "EFFECT_ALLOW"
        no = check_one(eng, P(id="boss", roles=["manager"]), R(attr={"managerId": "boss", "status": "DRAFT"}), ["approve"])
        assert no.actions["approve"].effect == "EFFECT_DENY"

    def test_missing_attr_is_false_not_error(self):
        out = check_one(make_engine(), P(id="boss", roles=["manager"]), R(attr={"managerId": "boss"}), ["approve"])
        assert out.actions["approve"].effect == "EFFECT_DENY"

    def test_wildcard_action_glob(self):
        eng = make_engine()
        out = check_one(eng, P(), R(attr={"owner": "john"}), ["view:private"])
        assert out.actions["view:private"].effect == "EFFECT_ALLOW"
        # ':' is the glob separator: view:* must not match a deeper segment path
        out2 = check_one(eng, P(), R(attr={"owner": "john"}), ["view:a:b"])
        assert out2.actions["view:a:b"].effect == "EFFECT_DENY"

    def test_admin_star_matches_everything(self):
        out = check_one(make_engine(), P(id="root", roles=["admin"]), R(), ["delete", "anything:at:all"])
        assert out.actions["delete"].effect == "EFFECT_ALLOW"
        assert out.actions["anything:at:all"].effect == "EFFECT_ALLOW"

    def test_roles_evaluated_independently(self):
        # Rule-table semantics (check.go:409-417): each role is evaluated
        # independently and the first independent ALLOW wins, so auditor's
        # delete-DENY does not block admin's wildcard ALLOW.
        out = check_one(make_engine(), P(id="x", roles=["auditor", "admin"]), R(), ["delete"])
        assert out.actions["delete"].effect == "EFFECT_ALLOW"
        # auditor alone is denied
        out2 = check_one(make_engine(), P(id="x", roles=["auditor"]), R(), ["delete"])
        assert out2.actions["delete"].effect == "EFFECT_DENY"

    def test_deny_beats_allow_within_role(self):
        # Within a single role, an explicit DENY breaks the scope walk even
        # when another rule allows (check.go:376-384).
        src = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: thing
  version: default
  rules:
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [worker]
    - actions: ["drop"]
      effect: EFFECT_DENY
      roles: [worker]
"""
        out = check_one(make_engine(src), P(id="w", roles=["worker"]), R(kind="thing"), ["drop", "push"])
        assert out.actions["drop"].effect == "EFFECT_DENY"
        assert out.actions["push"].effect == "EFFECT_ALLOW"

    def test_outputs(self):
        eng = make_engine()
        ok = check_one(eng, P(id="boss", roles=["manager"]), R(attr={"managerId": "boss", "status": "PENDING_APPROVAL"}), ["approve"])
        assert any(o.val == "approved by boss" for o in ok.outputs)
        no = check_one(eng, P(id="boss", roles=["manager"]), R(attr={"managerId": "boss", "status": "X"}), ["approve"])
        assert any(o.val == "not pending" for o in no.outputs)
        src = [o.src for o in ok.outputs][0]
        assert src.startswith("resource.leave_request.vdefault#")


class TestPrincipalPolicyPrecedence:
    def test_principal_deny_overrides_resource_allow(self):
        eng = make_engine()
        out = check_one(
            eng,
            P(id="daffy", roles=["manager"]),
            R(attr={"managerId": "daffy", "status": "PENDING_APPROVAL"}),
            ["approve"],
        )
        assert out.actions["approve"].effect == "EFFECT_DENY"
        assert out.actions["approve"].policy == "principal.daffy.vdefault"

    def test_principal_glob_resource(self):
        eng = make_engine()
        out = check_one(eng, P(id="daffy", roles=["employee"]), R(kind="secret_files"), ["view"])
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.actions["view"].policy == "principal.daffy.vdefault"

    def test_other_principals_unaffected(self):
        eng = make_engine()
        out = check_one(eng, P(id="donald", roles=["employee"]), R(kind="secret_files"), ["view"])
        assert out.actions["view"].effect == "EFFECT_DENY"
        assert out.actions["view"].policy == "NO_MATCH"


SCOPED_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view", "edit", "delete"]
      effect: EFFECT_ALLOW
      roles: [user]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  scope: acme
  rules:
    - actions: ["delete"]
      effect: EFFECT_DENY
      roles: [user]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  scope: acme.hr
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.confidential != true
"""


class TestScopes:
    def test_scope_fallthrough_to_root(self):
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr"), ["edit"])
        assert out.actions["edit"].effect == "EFFECT_ALLOW"
        assert out.actions["edit"].scope == ""

    def test_scope_deny_in_middle(self):
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr"), ["delete"])
        assert out.actions["delete"].effect == "EFFECT_DENY"
        assert out.actions["delete"].scope == "acme"

    def test_leaf_scope_allow_overrides(self):
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr", attr={"confidential": False}), ["view"])
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.actions["view"].scope == "acme.hr"

    def test_leaf_condition_false_falls_through(self):
        # OVERRIDE_PARENT (default): condition false in leaf → falls through
        # to parent scopes, root allows view
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr", attr={"confidential": True}), ["view"])
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.actions["view"].scope == ""

    def test_unknown_scope_strict(self):
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr.nosuch"), ["view"])
        assert out.actions["view"].effect == "EFFECT_DENY"
        assert out.actions["view"].policy == "NO_MATCH"

    def test_unknown_scope_lenient(self):
        eng = make_engine(SCOPED_POLICIES)
        out = check_one(
            eng, P(id="u", roles=["user"]), R(kind="doc", scope="acme.hr.nosuch"), ["view"],
            params=EvalParams(lenient_scope_search=True),
        )
        assert out.actions["view"].effect == "EFFECT_ALLOW"


RPC_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view", "edit"]
      effect: EFFECT_ALLOW
      roles: [user]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  scope: tenant
  scopePermissions: SCOPE_PERMISSIONS_REQUIRE_PARENTAL_CONSENT_FOR_ALLOWS
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.public == true
    - actions: ["edit"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


class TestScopePermissions:
    def test_rpc_condition_true_requires_parent(self):
        eng = make_engine(RPC_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="tenant", attr={"public": True}), ["view"])
        # child consents (condition true), parent allows → ALLOW from parent
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.actions["view"].scope == ""

    def test_rpc_condition_false_denies(self):
        eng = make_engine(RPC_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="tenant", attr={"public": False}), ["view"])
        # negated-condition DENY row fires in the child scope
        assert out.actions["view"].effect == "EFFECT_DENY"
        assert out.actions["view"].scope == "tenant"

    def test_rpc_unconditional_allow_defers_to_parent(self):
        eng = make_engine(RPC_POLICIES)
        out = check_one(eng, P(id="u", roles=["user"]), R(kind="doc", scope="tenant"), ["edit"])
        assert out.actions["edit"].effect == "EFFECT_ALLOW"
        assert out.actions["edit"].scope == ""


ROLE_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules: []
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  scope: acme
  rules:
    - actions: ["view", "edit", "delete", "share"]
      effect: EFFECT_ALLOW
      roles: [admin]
---
apiVersion: api.cerbos.dev/v1
rolePolicy:
  role: intern
  scope: acme
  parentRoles: [admin]
  rules:
    - resource: doc
      allowActions: ["view"]
---
apiVersion: api.cerbos.dev/v1
rolePolicy:
  role: contractor
  scope: acme
  parentRoles: [admin]
  rules:
    - resource: doc
      allowActions: ["view", "edit"]
      condition:
        match:
          expr: request.resource.attr.assigned == request.principal.id
"""


class TestRolePolicies:
    def test_role_policy_narrows_parent(self):
        eng = make_engine(ROLE_POLICIES)
        # intern inherits admin via parentRoles but is restricted to view
        out = check_one(eng, P(id="i1", roles=["intern"]), R(kind="doc", scope="acme"), ["view", "edit", "delete"])
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.actions["edit"].effect == "EFFECT_DENY"
        assert out.actions["edit"].policy == "role.intern.vdefault/acme"
        assert out.actions["delete"].effect == "EFFECT_DENY"

    def test_conditional_role_policy(self):
        eng = make_engine(ROLE_POLICIES)
        ok = check_one(eng, P(id="c1", roles=["contractor"]), R(kind="doc", scope="acme", attr={"assigned": "c1"}), ["edit"])
        assert ok.actions["edit"].effect == "EFFECT_ALLOW"
        no = check_one(eng, P(id="c1", roles=["contractor"]), R(kind="doc", scope="acme", attr={"assigned": "other"}), ["edit"])
        assert no.actions["edit"].effect == "EFFECT_DENY"

    def test_plain_admin_unaffected(self):
        eng = make_engine(ROLE_POLICIES)
        out = check_one(eng, P(id="a", roles=["admin"]), R(kind="doc", scope="acme"), ["delete"])
        assert out.actions["delete"].effect == "EFFECT_ALLOW"


VARIABLES_POLICIES = """
apiVersion: api.cerbos.dev/v1
exportVariables:
  name: common_vars
  definitions:
    flagged: request.resource.attr.flagged == true
---
apiVersion: api.cerbos.dev/v1
exportConstants:
  name: common_consts
  definitions:
    allowed_depts: ["eng", "hr"]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: report
  version: default
  variables:
    import: [common_vars]
    local:
      in_dept: request.principal.attr.dept in C.allowed_depts
      combo: variables.in_dept && !variables.flagged
  constants:
    import: [common_consts]
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: V.combo
"""


class TestVariablesAndConstants:
    def test_variable_chain(self):
        eng = make_engine(VARIABLES_POLICIES)
        ok = check_one(eng, P(id="u", roles=["user"], attr={"dept": "eng"}), R(kind="report", attr={"flagged": False}), ["view"])
        assert ok.actions["view"].effect == "EFFECT_ALLOW"
        no = check_one(eng, P(id="u", roles=["user"], attr={"dept": "sales"}), R(kind="report", attr={"flagged": False}), ["view"])
        assert no.actions["view"].effect == "EFFECT_DENY"
        no2 = check_one(eng, P(id="u", roles=["user"], attr={"dept": "eng"}), R(kind="report", attr={"flagged": True}), ["view"])
        assert no2.actions["view"].effect == "EFFECT_DENY"


class TestVersions:
    POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: api
  version: default
  rules:
    - actions: ["call"]
      effect: EFFECT_ALLOW
      roles: [user]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: api
  version: v2
  rules:
    - actions: ["call"]
      effect: EFFECT_DENY
      roles: [user]
"""

    def test_version_selection(self):
        eng = make_engine(self.POLICIES)
        d = check_one(eng, P(roles=["user"]), R(kind="api"), ["call"])
        assert d.actions["call"].effect == "EFFECT_ALLOW"
        v2 = check_one(eng, P(roles=["user"]), R(kind="api", version="v2"), ["call"])
        assert v2.actions["call"].effect == "EFFECT_DENY"
        v3 = check_one(eng, P(roles=["user"]), R(kind="api", version="v3"), ["call"])
        assert v3.actions["call"].policy == "NO_MATCH"


def test_delete_role_policy_removes_parent_inheritance():
    # review regression: deleting a role policy must stop its parentRoles grant
    eng = make_engine(ROLE_POLICIES)
    out = check_one(eng, P(id="i1", roles=["intern"]), R(kind="doc", scope="acme"), ["view"])
    assert out.actions["view"].effect == "EFFECT_ALLOW"
    eng.rule_table.delete_policy("cerbos.role.intern.vdefault/acme")
    out2 = check_one(eng, P(id="i1", roles=["intern"]), R(kind="doc", scope="acme"), ["view"])
    assert out2.actions["view"].effect == "EFFECT_DENY"


class TestDefaultVersionAndScopeParams:
    POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: gadget
  version: beta
  rules: []
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: gadget
  version: beta
  scope: acme
  rules:
    - actions: ["use"]
      effect: EFFECT_ALLOW
      roles: [user]
"""

    def test_default_policy_version_param(self):
        eng = make_engine(self.POLICIES)
        # version unset on the request resolves via defaultPolicyVersion
        out = check_one(
            eng, P(id="u", roles=["user"]), R(kind="gadget", scope="acme"), ["use"],
            params=EvalParams(default_policy_version="beta"),
        )
        assert out.actions["use"].effect == "EFFECT_ALLOW"
        out2 = check_one(eng, P(id="u", roles=["user"]), R(kind="gadget", scope="acme"), ["use"])
        assert out2.actions["use"].policy == "NO_MATCH"

    def test_default_scope_param(self):
        eng = make_engine(self.POLICIES)
        out = check_one(
            eng, P(id="u", roles=["user"]), R(kind="gadget"), ["use"],
            params=EvalParams(default_policy_version="beta", default_scope="acme"),
        )
        assert out.actions["use"].effect == "EFFECT_ALLOW"

    def test_lenient_vs_strict_scope(self):
        eng = make_engine(self.POLICIES)
        strict = check_one(
            eng, P(id="u", roles=["user"]), R(kind="gadget", scope="acme.sub.deep"), ["use"],
            params=EvalParams(default_policy_version="beta"),
        )
        assert strict.actions["use"].policy == "NO_MATCH"
        lenient = check_one(
            eng, P(id="u", roles=["user"]), R(kind="gadget", scope="acme.sub.deep"), ["use"],
            params=EvalParams(default_policy_version="beta", lenient_scope_search=True),
        )
        assert lenient.actions["use"].effect == "EFFECT_ALLOW"
        assert lenient.actions["use"].scope == "acme"


class TestExportedConstantsChain:
    POLICIES = """
apiVersion: api.cerbos.dev/v1
exportConstants:
  name: limits
  definitions:
    max_size: 100
    env: prod
---
apiVersion: api.cerbos.dev/v1
exportVariables:
  name: shared_vars
  definitions:
    oversized: R.attr.size > C.max_size
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: upload
  version: default
  variables:
    import: [shared_vars]
  constants:
    import: [limits]
  rules:
    - actions: ["store"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          all:
            of:
              - expr: "!V.oversized"
              - expr: C.env == "prod"
"""

    def test_imported_constants_in_imported_variables(self):
        eng = make_engine(self.POLICIES)
        ok = check_one(eng, P(id="u", roles=["user"]), R(kind="upload", attr={"size": 50}), ["store"])
        assert ok.actions["store"].effect == "EFFECT_ALLOW"
        no = check_one(eng, P(id="u", roles=["user"]), R(kind="upload", attr={"size": 500}), ["store"])
        assert no.actions["store"].effect == "EFFECT_DENY"
