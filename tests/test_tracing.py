"""End-to-end device-path tracing and the batch flight recorder.

The ISSUE acceptance check lives here: a CheckResources call carrying a W3C
``traceparent`` header produces a single trace in which the device batch's
submit/collect spans are descendants of the request span across the batcher
thread hop, and ``/_cerbos/debug/flight`` returns the corresponding batch
record with non-zero stage timings and occupancy <= 1.0. Plus: the metrics
lint over the registry, flight-recorder unit behavior, and breaker
state-transition accounting.
"""

import json
import re
import threading
import time
import urllib.request

from cerbos_tpu import observability as obs
from cerbos_tpu.bootstrap import initialize
from cerbos_tpu.config import Config
from cerbos_tpu.engine import flight
from cerbos_tpu.engine.flight import FlightRecorder
from cerbos_tpu.engine.health import DeviceHealth

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id
"""


class _CaptureExporter(obs.SpanExporter):
    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def export(self, span, duration_ms):
        with self._lock:
            self.spans.append(span)

    def in_trace(self, trace_id):
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]


def _boot(tmp_path_factory, name):
    policy_dir = tmp_path_factory.mktemp(name)
    (policy_dir / "album.yaml").write_text(POLICY)
    config = Config.load(overrides=[f"storage.disk.directory={policy_dir}"])
    core = initialize(config)
    core.tpu_evaluator.use_jax = False  # keep the test jax-independent
    return core


class TestEndToEndTracing:
    def test_traceparent_joins_device_batch_trace(self, tmp_path_factory):
        """The acceptance check: one trace from the remote caller down to the
        device batch, stitched across the batcher thread hop, plus the
        matching flight-recorder record."""
        from cerbos_tpu.server.server import Server, ServerConfig

        core = _boot(tmp_path_factory, "tracing-policies")
        cap = _CaptureExporter()
        old_exporter = obs._exporter
        obs.set_exporter(cap)
        srv = Server(
            core.service,
            ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
        )
        srv.start()
        trace_id = obs.new_trace_id()
        remote_span_id = obs.new_span_id()
        header = f"00-{trace_id}-{remote_span_id}-01"
        try:
            body = {
                "requestId": "tr-1",
                "principal": {"id": "alice", "roles": ["user"]},
                "resources": [
                    {
                        "actions": ["view"],
                        "resource": {"kind": "album", "id": "a1", "attr": {"owner": "alice"}},
                    }
                ],
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.http_port}/api/check/resources",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json", "traceparent": header},
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["results"]
                # the response tells the caller which trace the PDP joined
                assert resp.headers.get("traceparent") == header

            # batch.collect / request.settle export on the drain thread just
            # after the response future resolves: wait for them briefly
            want = {
                "request.CheckResources",
                "batcher.enqueue",
                "batch.submit",
                "batch.collect",
                "request.settle",
            }
            deadline = time.time() + 10
            while time.time() < deadline:
                if want <= {s.name for s in cap.in_trace(trace_id)}:
                    break
                time.sleep(0.02)
            trace = cap.in_trace(trace_id)
            names = {s.name for s in trace}
            assert want <= names, sorted(names)

            spans = {s.name: s for s in trace}
            by_id = {s.span_id: s for s in trace}

            # batch.submit is a DESCENDANT of the remote request span even
            # though it runs on the batcher drain thread
            chain = []
            cur = spans["batch.submit"]
            while cur.parent_id in by_id:
                cur = by_id[cur.parent_id]
                chain.append(cur.name)
            assert "batcher.enqueue" in chain and "request.CheckResources" in chain, chain
            # ...and the topmost local span parents under the remote caller's id
            assert cur.parent_id == remote_span_id

            # the rest of the batch pipeline hangs off the batch span
            assert spans["batch.collect"].parent_id == spans["batch.submit"].span_id
            assert spans["request.settle"].parent_id == spans["batch.submit"].span_id
            # the batch span links every co-batched request's context
            assert spans["batcher.enqueue"].context in spans["batch.submit"].links

            # flight recorder: the batch record for this trace is retrievable
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.http_port}/_cerbos/debug/flight"
            ) as resp:
                dump = json.loads(resp.read())
            recs = [r for r in dump["batches"] if trace_id in r["trace_ids"]]
            assert recs, dump
            rec = recs[-1]
            assert rec["outcome"] == "ok"
            assert rec["occupancy"] is not None and rec["occupancy"] <= 1.0
            assert any(v > 0 for v in rec["timings"].values()), rec
            assert rec["requests"] >= 1 and rec["inputs"] >= 1
        finally:
            obs.set_exporter(old_exporter)
            srv.stop()
            core.close()


class TestMetricsLint:
    def test_registry_lints_clean_after_bootstrap(self, tmp_path_factory):
        """Every registered instrument: conformant name, help text, and a
        single known instrument type (the registry raising on conflicts is
        covered in test_observability)."""
        core = _boot(tmp_path_factory, "lint-policies")
        try:
            # the async audit path registers its queue metrics at
            # construction; default config has audit off, so build one here
            from cerbos_tpu.audit.log import AuditLog

            AuditLog(backend=None).close()
            inst = obs.metrics().instruments()
            # the device-path instruments this PR adds must be registered
            for name in (
                "cerbos_tpu_batch_occupancy",
                "cerbos_tpu_batch_padding_waste_rows_total",
                "cerbos_tpu_batch_stage_seconds",
                "cerbos_tpu_breaker_state",
                "cerbos_tpu_breaker_transitions_total",
                # compile-economy family (docs/OBSERVABILITY.md)
                "cerbos_tpu_xla_compiles_total",
                "cerbos_tpu_xla_compile_seconds",
                "cerbos_tpu_jit_cache_hits_total",
                "cerbos_tpu_jit_cache_misses_total",
                "cerbos_tpu_xla_layout_cardinality",
                "cerbos_tpu_recompile_storms_total",
                "cerbos_tpu_readiness_state",
                "cerbos_tpu_warmup_expected_layouts",
                "cerbos_tpu_warmup_compiled_layouts",
                # parity-sentinel family (engine/sentinel.py): bootstrap
                # attaches the sentinel to every local batcher by default
                "cerbos_tpu_parity_checks_total",
                "cerbos_tpu_parity_divergence_total",
                "cerbos_tpu_parity_lag_seconds",
                "cerbos_tpu_parity_sample_rate",
                "cerbos_tpu_parity_dropped_total",
                "cerbos_tpu_parity_replay_seconds_total",
                "cerbos_tpu_parity_storms_total",
                "cerbos_tpu_parity_corpus_records",
                # async audit-path family (audit/log.py)
                "cerbos_tpu_audit_queue_depth",
                "cerbos_tpu_audit_dropped_total",
                # latency-budget waterfall + goodput family (engine/budget.py)
                "cerbos_tpu_request_stage_seconds",
                "cerbos_tpu_request_total_seconds",
                "cerbos_tpu_deadline_budget_remaining_seconds",
                "cerbos_tpu_decisions_total",
                "cerbos_tpu_slow_requests_total",
                # saturation pressure family (engine/pressure.py)
                "cerbos_tpu_pressure_score",
                "cerbos_tpu_pressure_queue",
                "cerbos_tpu_pressure_inflight",
                "cerbos_tpu_pressure_ipc",
                "cerbos_tpu_pressure_fallback",
                "cerbos_tpu_pressure_degraded",
                "cerbos_tpu_pressure_compile",
                # static policy analysis family (tpu/analyze.py): bootstrap
                # publishes a report for the boot table and re-publishes on
                # every swap; the compile-rejection counter registers with
                # the condition compiler itself
                "cerbos_tpu_policy_analysis_total",
                "cerbos_tpu_cond_compile_unsupported_total",
                # batched PlanResources family (plan/batch.py + the plan-mode
                # parity leg in engine/sentinel.py)
                "cerbos_tpu_plan_batch_seconds",
                "cerbos_tpu_plan_queries_total",
                "cerbos_tpu_plan_residual_rules",
                "cerbos_tpu_plan_parity_checks_total",
                "cerbos_tpu_plan_parity_divergence_total",
                # safe policy rollout family (engine/rollout.py); the skew
                # gauge is frontend-only (ipc client) so it is not listed
                "cerbos_tpu_rollout_total",
                "cerbos_tpu_rollout_duration_seconds",
                "cerbos_tpu_policy_epoch",
                # decision-provenance family (engine/hotrules.py): the
                # batcher instantiates the recorder at construction so the
                # series exist before the first decision
                "cerbos_tpu_rule_hits_total",
                "cerbos_tpu_decision_source_total",
            ):
                assert name in inst, name
            known = (obs.Counter, obs.CounterVec, obs.Gauge, obs.GaugeVec, obs.Histogram, obs.HistogramVec)
            for name, m in inst.items():
                assert re.fullmatch(r"cerbos_tpu_[a-z0-9_]+", name), name
                assert isinstance(m, known), (name, type(m))
                assert m.help, f"metric {name!r} has no help text"
            # sharded serving (docs/OBSERVABILITY.md "Per-shard row"): these
            # families carry a shard label so one sick chip is visible as
            # ONE sick series, not a poisoned aggregate
            sharded = {
                "cerbos_tpu_batcher_inflight": obs.GaugeVec,
                "cerbos_tpu_batch_occupancy": obs.GaugeVec,
                "cerbos_tpu_breaker_state": obs.GaugeVec,
                "cerbos_tpu_batch_padding_waste_rows_total": obs.CounterVec,
                "cerbos_tpu_breaker_trips_total": obs.CounterVec,
                "cerbos_tpu_parity_checks_total": obs.CounterVec,
                "cerbos_tpu_parity_divergence_total": obs.CounterVec,
                "cerbos_tpu_parity_storms_total": obs.CounterVec,
            }
            for name, typ in sharded.items():
                m = inst.get(name)
                assert isinstance(m, typ), (name, type(m))
                label = m.label if isinstance(m.label, str) else None
                assert label == "shard", (name, m.label)
            # multi-dimension vecs keep shard as the LAST label dimension
            for name in (
                "cerbos_tpu_batch_stage_seconds",
                "cerbos_tpu_breaker_transitions_total",
                "cerbos_tpu_request_stage_seconds",
                "cerbos_tpu_deadline_budget_remaining_seconds",
            ):
                m = inst.get(name)
                assert isinstance(m.label, tuple) and m.label[-1] == "shard", (name, m.label)
            # goodput accounting splits on (api, outcome) so PlanResources
            # traffic is booked alongside checks (process-global)
            m = inst.get("cerbos_tpu_decisions_total")
            assert isinstance(m, obs.CounterVec) and m.label == ("api", "outcome"), m.label
            # rollout stage accounting splits on (stage, outcome) so a gate
            # rejection and a canary rollback are distinct series
            m = inst.get("cerbos_tpu_rollout_total")
            assert isinstance(m, obs.CounterVec) and m.label == ("stage", "outcome"), m.label
            m = inst.get("cerbos_tpu_rollout_duration_seconds")
            assert isinstance(m, obs.HistogramVec) and m.label == "stage", m.label
            # rendered exposition carries the label on every child series
            text = obs.metrics().render()
            for line in text.splitlines():
                if line.startswith("cerbos_tpu_breaker_state{"):
                    assert 'shard="' in line, line
        finally:
            core.close()


class TestFlightRecorder:
    def _record(self, rec, batch_id, **kw):
        defaults = dict(
            trace_ids=[], requests=1, inputs=1, timings={"submit": 0.001}, outcome="ok"
        )
        defaults.update(kw)
        rec.record_batch(batch_id, **defaults)

    def test_capacity_bound_drops_oldest(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            self._record(rec, i)
        dump = rec.dump()
        assert dump["capacity"] == 4
        assert [r["batch_id"] for r in dump["batches"]] == [6, 7, 8, 9]

    def test_event_ring_is_bounded_too(self):
        rec = FlightRecorder(capacity=2)
        for i in range(3):
            rec.record_event("bisect_done", idx=i)
        evs = rec.dump()["events"]
        assert [e["idx"] for e in evs] == [1, 2]
        assert all(e["kind"] == "bisect_done" and e["ts"] > 0 for e in evs)

    def test_disabled_records_nothing(self):
        rec = FlightRecorder(capacity=4, enabled=False)
        self._record(rec, 1)
        rec.record_event("x")
        assert rec.dump() == {"capacity": 4, "batches": [], "events": []}

    def test_record_fields_and_rounding(self):
        rec = FlightRecorder()
        self._record(
            rec,
            7,
            trace_ids=["t1", "t2"],
            timings={"pack": 0.123456789},
            occupancy=0.87654321,
            layout_key="B64xBA128",
            breaker_state="closed",
        )
        r = rec.dump()["batches"][0]
        assert r["timings"]["pack"] == 0.123457
        assert r["occupancy"] == 0.8765
        assert r["layout_key"] == "B64xBA128"
        assert r["breaker_state"] == "closed"
        assert r["trace_ids"] == ["t1", "t2"]

    def test_batch_ids_monotonic(self):
        rec = FlightRecorder()
        assert rec.next_batch_id() < rec.next_batch_id()

    def test_clear(self):
        rec = FlightRecorder()
        self._record(rec, 1)
        rec.record_event("x")
        rec.clear()
        dump = rec.dump()
        assert dump["batches"] == [] and dump["events"] == []

    def test_configure_mutates_global_in_place(self):
        """Bootstrap re-bounds the process recorder without replacing it, so
        modules holding a reference keep recording into the live ring."""
        rec = flight.recorder()
        old_capacity, old_enabled = rec.capacity, rec.enabled
        try:
            got = flight.configure(capacity=3, enabled=True)
            assert got is rec and flight.recorder() is rec
            assert rec.capacity == 3
            for i in range(5):
                rec.record_event("cfg_probe", i=i)
            assert len(rec.dump()["events"]) <= 3
        finally:
            flight.configure(capacity=old_capacity, enabled=old_enabled)


class TestBreakerTransitions:
    def test_each_edge_is_counted_and_recorded(self):
        clock = [0.0]
        h = DeviceHealth(
            failure_threshold=2,
            probe_backoff_base_s=0.1,
            probe_backoff_cap_s=0.1,
            clock=lambda: clock[0],
        )
        vec = h.m_transitions  # global counter_vec: compare deltas, not totals
        # children keyed (transition, shard); an unsharded breaker is shard "0"
        edges = tuple(
            (t, "0") for t in ("closed_open", "open_half_open", "half_open_open", "half_open_closed")
        )
        base = {e: vec.get(e) for e in edges}
        ev_base = len(
            [e for e in flight.recorder().dump()["events"] if e["kind"] == "breaker_transition"]
        )

        h.record_failure()
        assert h.state == "closed"  # below threshold: no transition yet
        h.record_failure()
        assert h.state == "open"
        assert vec.get(("closed_open", "0")) == base[("closed_open", "0")] + 1
        assert h.m_state.value == 1.0

        clock[0] += 1000.0
        token = h.should_probe()
        assert token is not None
        assert vec.get(("open_half_open", "0")) == base[("open_half_open", "0")] + 1
        assert h.m_state.value == 2.0

        h.probe_failed(token)
        assert vec.get(("half_open_open", "0")) == base[("half_open_open", "0")] + 1

        clock[0] += 1000.0
        token = h.should_probe()
        assert token is not None
        h.probe_succeeded(token)
        assert h.state == "closed"
        assert vec.get(("half_open_closed", "0")) == base[("half_open_closed", "0")] + 1
        assert h.m_state.value == 0.0

        # 5 edges total: trip, half-open, re-open, half-open, re-close
        trans = [
            e for e in flight.recorder().dump()["events"] if e["kind"] == "breaker_transition"
        ]
        assert len(trans) == ev_base + 5
        assert (trans[-1]["frm"], trans[-1]["to"]) == ("half_open", "closed")
