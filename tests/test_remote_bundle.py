"""Remote bundle source: download, ETag poll, backoff, serve-cached.

Mirrors the mechanism of internal/storage/hub/remote_source.go against a
local in-process HTTP server (no egress).
"""

import hashlib
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from cerbos_tpu.bundle import build_bundle
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, Principal, Resource
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.storage import DiskStore, new_store
from cerbos_tpu.storage.remote_bundle import RemoteBundleError, RemoteBundleStore

POLICY_V1 = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""

POLICY_V2 = POLICY_V1.replace('["view"]', '["view", "edit"]')


class _BundleServer:
    """Serves one bundle blob with ETag semantics; togglable failure mode."""

    def __init__(self):
        self.blob = b""
        self.etag = ""
        self.fail = False
        self.requests = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                outer.requests.append(dict(self.headers))
                if outer.fail:
                    self.send_error(503, "down")
                    return
                if self.headers.get("If-None-Match") == outer.etag:
                    self.send_response(304)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("ETag", outer.etag)
                self.send_header("Content-Length", str(len(outer.blob)))
                self.end_headers()
                self.wfile.write(outer.blob)

            def log_message(self, *a):
                pass

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def set_bundle(self, blob: bytes):
        self.blob = blob
        self.etag = '"%s"' % hashlib.sha256(blob).hexdigest()[:16]

    def stop(self):
        self.httpd.shutdown()


def _bundle_bytes(tmp_path, policy: str, name: str) -> bytes:
    d = tmp_path / f"src-{name}"
    d.mkdir()
    (d / "doc.yaml").write_text(policy)
    out = tmp_path / f"{name}.crbp"
    build_bundle(DiskStore(str(d)), str(out))
    return out.read_bytes()


@pytest.fixture()
def server(tmp_path):
    srv = _BundleServer()
    srv.set_bundle(_bundle_bytes(tmp_path, POLICY_V1, "v1"))
    yield srv
    srv.stop()


def _effect(store, action="edit"):
    rt = build_rule_table(compile_policy_set(store.get_all()))
    out = check_input(
        rt,
        CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind="doc", id="d1"),
            actions=[action],
        ),
        __import__("cerbos_tpu.engine", fromlist=["EvalParams"]).EvalParams(),
    )
    return out.actions[action].effect


def test_boot_download_and_poll_update(server, tmp_path):
    store = RemoteBundleStore(
        url=f"http://127.0.0.1:{server.port}/bundle",
        cache_dir=str(tmp_path / "cache"),
        _start_poll=False,
    )
    assert store.stats["downloads"] == 1
    assert len(store.get_all()) == 1
    assert _effect(store) == "EFFECT_DENY"

    # unchanged: conditional GET gets 304, no swap
    assert store.poll_once() is False
    assert store.stats["not_modified"] == 1

    # new bundle appears: poll swaps it in and notifies subscribers
    events = []
    store.subscribe(lambda evs: events.append(evs))
    server.set_bundle(_bundle_bytes(tmp_path, POLICY_V2, "v2"))
    assert store.poll_once() is True
    assert _effect(store) == "EFFECT_ALLOW"
    assert events and events[0][0].kind == "RELOAD"
    store.close()


def test_endpoint_dies_midrun_keeps_serving(server, tmp_path):
    store = RemoteBundleStore(
        url=f"http://127.0.0.1:{server.port}/bundle",
        cache_dir=str(tmp_path / "cache"),
        _start_poll=False,
    )
    server.fail = True
    assert store.poll_once() is False
    assert store.poll_once() is False
    assert store.stats["failures"] == 2
    assert store._failures == 2  # drives exponential backoff in the poll loop
    # still serving the cached bundle
    assert len(store.get_all()) == 1
    store.close()


def test_boot_from_cache_when_endpoint_down(server, tmp_path):
    cache = tmp_path / "cache"
    store = RemoteBundleStore(
        url=f"http://127.0.0.1:{server.port}/bundle",
        cache_dir=str(cache),
        _start_poll=False,
    )
    store.close()
    server.fail = True
    # reboot against a dead endpoint: cached bundle serves
    store2 = RemoteBundleStore(
        url=f"http://127.0.0.1:{server.port}/bundle",
        cache_dir=str(cache),
        _start_poll=False,
    )
    assert store2.stats["served_from_cache_boot"] is True
    assert len(store2.get_all()) == 1
    store2.close()


def test_boot_fails_without_cache(server, tmp_path):
    server.fail = True
    with pytest.raises(RemoteBundleError):
        RemoteBundleStore(
            url=f"http://127.0.0.1:{server.port}/bundle",
            cache_dir=str(tmp_path / "empty-cache"),
            _start_poll=False,
        )


def test_driver_registry(server, tmp_path):
    store = new_store(
        {
            "driver": "remoteBundle",
            "remoteBundle": {
                "url": f"http://127.0.0.1:{server.port}/bundle",
                "cacheDir": str(tmp_path / "cache"),
                "pollIntervalSeconds": 0,
            },
        }
    )
    assert len(store.get_all()) == 1
    store.close()
