"""The zero-copy front door (PR 10): shm frame rings + native codec.

Three layers, bottom up: the native ring/codec kernels in isolation, the
transport negotiation ladder (shm granted only when both ends can run it,
uds otherwise — never a failed boot), and the full degradation story on the
shm data plane: ring-full backpressure onto the oracle, wedged-ring
swallowing, batcher death mid-flight with zero lost requests, and reattach
re-granting shm after the batcher returns.

Every test here must ALSO pass with ``CERBOS_TPU_NO_NATIVE=1`` (the suite
skips what can't run and proves the uds fallback for the rest) — CI runs
both legs.
"""

import json
import os
import threading
import time

import pytest

from cerbos_tpu import native
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine.batcher import BatchingEvaluator
from cerbos_tpu.engine.ipc import (
    BatcherIpcServer,
    RemoteBatcherClient,
    _ShmSegment,
    decode_inputs,
    encode_inputs,
)
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""

needs_native = pytest.mark.skipif(
    native.get() is None, reason="native module unavailable (CERBOS_TPU_NO_NATIVE?)"
)


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
        request_id=f"rq{i}",
    )


def effects(outs):
    return [{a: (e.effect, e.policy) for a, e in o.actions.items()} for o in outs]


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


class OracleEvaluator:
    def __init__(self, rt, submit_delay_s: float = 0.0):
        self.rule_table = rt
        self.schema_mgr = None
        self.submit_delay_s = submit_delay_s
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return oracle(self.rule_table, inputs, params)

    def submit(self, inputs, params=None):
        if self.submit_delay_s:
            time.sleep(self.submit_delay_s)
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


def wait_for(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def rt():
    return table()


def make_pair(
    tmp_path,
    rt,
    server_transport="shm",
    client_transport="shm",
    submit_delay_s=0.0,
    faults=None,
    request_timeout_s=30.0,
    ring_kib=1024,
    max_outstanding=4096,
):
    batcher = BatchingEvaluator(
        OracleEvaluator(rt, submit_delay_s=submit_delay_s), max_wait_ms=1.0
    )
    server = BatcherIpcServer(
        str(tmp_path / "batcher.sock"),
        batcher,
        max_outstanding=max_outstanding,
        faults=faults,
        transport=server_transport,
    )
    server.start()
    client = RemoteBatcherClient(
        server.socket_path,
        rt,
        request_timeout_s=request_timeout_s,
        worker_label="fe-shm-test",
        status_poll_s=0.05,
        connect_retry_s=0.05,
        transport=client_transport,
        ring_kib=ring_kib,
    )
    assert wait_for(client._connected.is_set)
    return batcher, server, client


def close_pair(batcher, server, client):
    client.close()
    server.close()
    batcher.close()


# -- native ring kernels -----------------------------------------------------


@needs_native
class TestRing:
    RING = 1 << 16

    def _ring(self):
        buf = bytearray(256 + self.RING)
        native.get().ring_init(memoryview(buf))
        return memoryview(buf)

    def test_push_pop_fifo_with_wraparound(self):
        nat = native.get()
        mv = self._ring()
        # payloads sized so the ring wraps many times over the run
        for i in range(2000):
            payload = bytes([i & 0xFF]) * (100 + (i % 700))
            assert nat.ring_push(mv, 3, i, payload)
            got = nat.ring_pop(mv)
            assert got == (3, i, payload)
        assert nat.ring_pop(mv) is None
        used, cap, pushed, popped, full = nat.ring_stats(mv)
        assert used == 0 and cap == self.RING
        assert pushed == popped == 2000

    def test_interleaved_backlog_preserves_order(self):
        nat = native.get()
        mv = self._ring()
        for i in range(50):
            assert nat.ring_push(mv, 7, i, b"x" * i)
        for i in range(50):
            assert nat.ring_pop(mv) == (7, i, b"x" * i)

    def test_full_ring_refuses_and_counts(self):
        nat = native.get()
        mv = self._ring()
        n = 0
        while nat.ring_push(mv, 1, n, b"y" * 1000):
            n += 1
        assert 0 < n < 70  # 64KiB ring, ~1KiB records
        assert not nat.ring_push(mv, 1, n, b"y" * 1000)
        *_, full_events = nat.ring_stats(mv)
        assert full_events >= 2
        # draining one record frees space for exactly one more
        assert nat.ring_pop(mv) is not None
        assert nat.ring_push(mv, 1, n, b"y" * 1000)

    def test_oversized_frame_raises(self):
        nat = native.get()
        mv = self._ring()
        with pytest.raises(ValueError):
            nat.ring_push(mv, 1, 0, b"z" * (self.RING + 16))

    def test_wait_times_out_then_wakes_cross_thread(self):
        nat = native.get()
        mv = self._ring()
        seq = nat.ring_seq(mv, 0)
        t0 = time.monotonic()
        nat.ring_wait(mv, 0, seq, 80)
        assert time.monotonic() - t0 >= 0.05  # actually blocked

        woke = threading.Event()

        def waiter():
            s = nat.ring_seq(mv, 0)
            nat.ring_wait(mv, 0, s, 5000)
            woke.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.05)
        nat.ring_push(mv, 1, 0, b"ping")
        assert woke.wait(2.0), "push did not wake the futex waiter"
        t.join(timeout=2.0)


# -- native frame codec ------------------------------------------------------


@needs_native
class TestFrameCodec:
    def test_ticket_roundtrip_matches_marshal_codec(self, rt):
        import cerbos_tpu.engine.types as T

        nat = native.get()
        inputs = [
            inp(i, note="café \U0001f680", nested={"a": [1, 2.5, None, True]})
            for i in range(9)
        ]
        frame = nat.ticket_pack(inputs, 1.25, "00-ab-cd-01", (0.002, [["stage", 0.001]]))
        deadline_rel, traceparent, decoded, carry = nat.ticket_unpack(
            frame, T.Principal, T.Resource, T.AuxData, T.CheckInput
        )
        assert deadline_rel == 1.25
        assert traceparent == "00-ab-cd-01"
        # containers decode as lists (the carry spec is shape-compatible)
        assert carry == [0.002, [["stage", 0.001]]]
        # decision parity against the marshal codec path AND the originals
        legacy = decode_inputs(encode_inputs(inputs))
        assert effects(oracle(rt, decoded)) == effects(oracle(rt, legacy))
        assert [d.request_id for d in decoded] == [i.request_id for i in inputs]
        assert decoded[3].resource.attr["note"] == "café \U0001f680"
        assert decoded[3].resource.attr["nested"] == {"a": [1, 2.5, None, True]}

    def test_ticket_none_deadline_and_carry(self, rt):
        import cerbos_tpu.engine.types as T

        nat = native.get()
        frame = nat.ticket_pack([inp(0)], None, None, None)
        deadline_rel, traceparent, decoded, carry = nat.ticket_unpack(
            frame, T.Principal, T.Resource, T.AuxData, T.CheckInput
        )
        assert deadline_rel is None and traceparent is None and carry is None
        assert len(decoded) == 1

    def test_reply_roundtrip(self, rt):
        import cerbos_tpu.engine.types as T

        nat = native.get()
        outs = oracle(rt, [inp(i) for i in range(9)])
        spec = (0.004, [["device_submit", 0.003]], "device", None, 2)
        frame = nat.reply_pack(outs, spec)
        decoded, got_spec = nat.reply_unpack(
            frame, T.CheckOutput, T.ActionEffect, T.ValidationError, T.OutputEntry
        )
        assert effects(decoded) == effects(outs)
        assert [d.resource_id for d in decoded] == [o.resource_id for o in outs]
        assert got_spec == [0.004, [["device_submit", 0.003]], "device", None, 2] or tuple(
            got_spec
        ) == spec

    def test_truncated_frames_raise_not_crash(self, rt):
        import cerbos_tpu.engine.types as T

        nat = native.get()
        frame = nat.ticket_pack([inp(i) for i in range(3)], 1.0, None, None)
        for cut in (0, 1, 5, len(frame) // 2, len(frame) - 1):
            with pytest.raises(ValueError):
                nat.ticket_unpack(
                    frame[:cut], T.Principal, T.Resource, T.AuxData, T.CheckInput
                )


# -- negotiation ladder ------------------------------------------------------


class TestNegotiation:
    def test_shm_granted_when_both_sides_native(self, tmp_path, rt):
        if native.get() is None:
            pytest.skip("native module unavailable")
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            assert client.transport == "shm"
            assert server.stats["shm_conns"] == 1
            # the segment name is unlinked right after the grant: a SIGKILL
            # on either side cannot leak segments into /dev/shm
            assert client._shm is not None
            assert not os.path.exists(client._shm.path)
        finally:
            close_pair(batcher, server, client)

    def test_server_forced_uds_downgrades_shm_client(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt, server_transport="uds")
        try:
            assert client.transport == "uds"
            inputs = [inp(i) for i in range(8)]
            assert effects(client.check(inputs)) == effects(oracle(rt, inputs))
            assert client.stats["oracle_fallbacks"] == 0
        finally:
            close_pair(batcher, server, client)

    def test_client_forced_uds_never_offers_shm(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt, client_transport="uds")
        try:
            assert client.transport == "uds"
            assert server.stats["shm_conns"] == 0
            inputs = [inp(i) for i in range(8)]
            assert effects(client.check(inputs)) == effects(oracle(rt, inputs))
        finally:
            close_pair(batcher, server, client)

    def test_missing_native_module_falls_back_to_uds(self, tmp_path, rt, monkeypatch):
        """A front end without the built .so (heterogeneous fleet) keeps
        working: the HELLO never offers shm and traffic rides the socket."""
        import cerbos_tpu.engine.ipc as ipc_mod

        monkeypatch.setattr(ipc_mod.native, "get", lambda: None)
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            assert client.transport == "uds"
            inputs = [inp(i) for i in range(8)]
            assert effects(client.check(inputs)) == effects(oracle(rt, inputs))
        finally:
            close_pair(batcher, server, client)

    def test_segment_layout_validation_rejects_garbage(self, tmp_path):
        p = tmp_path / "bogus.shm"
        p.write_bytes(b"\x00" * 8192)
        with pytest.raises(Exception):
            _ShmSegment.attach(str(p))


# -- shm data plane ----------------------------------------------------------


@needs_native
class TestShmDataPlane:
    def test_decision_parity_and_stats(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            assert client.transport == "shm"
            inputs = [inp(i) for i in range(64)]
            remote = client.check(inputs)
            assert effects(remote) == effects(batcher.check(inputs))
            assert effects(remote) == effects(oracle(rt, inputs))
            assert client.stats["oracle_fallbacks"] == 0
            ts = client.transport_stats()
            assert ts["transport"] == "shm"
            assert ts["frames_out"] >= 1 and ts["frames_in"] >= 1
            assert ts["encode_ns_per_frame"] > 0 and ts["decode_ns_per_frame"] > 0
            assert json.dumps(ts)  # loadtest/bench embed this verbatim
        finally:
            close_pair(batcher, server, client)

    def test_check_await_parity_on_shm(self, tmp_path, rt):
        import asyncio

        batcher, server, client = make_pair(tmp_path, rt)
        try:
            assert client.transport == "shm"

            async def go():
                return await client.check_await([inp(i) for i in range(16)])

            remote = asyncio.run(go())
            assert effects(remote) == effects(oracle(rt, [inp(i) for i in range(16)]))
        finally:
            close_pair(batcher, server, client)

    def test_concurrent_frontend_threads_multiplex_one_ring(self, tmp_path, rt):
        """Many request threads share one client (the aiohttp process model):
        the GIL serializes ring pushes and req_ids demultiplex settles."""
        batcher, server, client = make_pair(tmp_path, rt)
        results = {}
        try:
            assert client.transport == "shm"

            def worker(tid):
                inputs = [inp(tid * 100 + j) for j in range(10)]
                results[tid] = (effects(client.check(inputs)), effects(oracle(rt, inputs)))

            threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30.0)
            assert len(results) == 8
            for got, want in results.values():
                assert got == want
        finally:
            close_pair(batcher, server, client)

    def test_oversized_ticket_sheds_to_oracle_as_ipc_full(self, tmp_path, rt):
        """A frame that cannot ever fit the ring is a backpressure event,
        not an error: the front end serves its oracle and counts it."""
        batcher, server, client = make_pair(tmp_path, rt, ring_kib=64)
        try:
            assert client.transport == "shm"
            big = [inp(i, blob="x" * 4096) for i in range(40)]  # >64KiB packed
            outs = client.check(big)
            assert effects(outs) == effects(oracle(rt, big))
            assert client.stats["ring_full"] >= 1
            assert client.stats["oracle_fallbacks"] >= 1
            assert client.m_fallbacks.get("ipc_full") >= 1
        finally:
            close_pair(batcher, server, client)

    def test_wedged_ring_swallows_tickets_then_oracle(self, tmp_path, rt):
        """engine/faults.py ipc_wedge_after generalized to the shm plane:
        past the threshold the batcher swallows tickets off the ring, the
        front end times out, and the request settles from the oracle."""
        batcher, server, client = make_pair(
            tmp_path, rt, faults={"ipc_wedge_after": 2}, request_timeout_s=0.5
        )
        try:
            assert client.transport == "shm"
            for i in range(3):
                assert effects(client.check([inp(i)])) == effects(oracle(rt, [inp(i)]))
            # past the wedge threshold: swallowed off the ring, oracle serves
            out = client.check([inp(99)])
            assert effects(out) == effects(oracle(rt, [inp(99)]))
            assert server.stats["wedged_drops"] >= 1
            assert client.m_fallbacks.get("ipc_timeout") >= 1
        finally:
            close_pair(batcher, server, client)

    def test_batcher_death_midflight_loses_zero_requests(self, tmp_path, rt):
        """The chaos pin on the shm plane: the batcher dies with tickets on
        the ring. Liveness rides the SOCKET (the shm mapping would survive a
        dead peer silently), so the close fails pending futures immediately
        and every request settles from the COW oracle."""
        batcher, server, client = make_pair(tmp_path, rt, submit_delay_s=0.3)
        results = []
        try:
            assert client.transport == "shm"

            def worker(i):
                results.append(effects(client.check([inp(i)])))

            threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
            for t in threads:
                t.start()
            time.sleep(0.1)  # tickets in flight on the ring
            server.close()
            batcher.close()
            for t in threads:
                t.join(timeout=15.0)
            assert len(results) == 6, "requests were lost on batcher death"
            for i, eff in enumerate(results):
                assert eff  # settled with a real decision, not an exception
            assert client.stats["oracle_fallbacks"] >= 1
            assert client.transport == "none"
        finally:
            client.close()

    def test_reattach_regrants_shm_after_batcher_returns(self, tmp_path, rt):
        """detach -> oracle -> reattach: a respawned batcher on the same
        socket re-runs the HELLO negotiation and the data plane comes back
        as shm, with a fresh segment (the old one died with the peer)."""
        batcher, server, client = make_pair(tmp_path, rt)
        sock_path = server.socket_path
        try:
            assert client.transport == "shm"
            first_seg = client._shm
            server.close()
            batcher.close()
            assert wait_for(lambda: not client._connected.is_set())
            # down: the oracle serves
            assert effects(client.check([inp(1)])) == effects(oracle(rt, [inp(1)]))
            assert client.transport == "none"
            # respawn on the same path
            batcher2 = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
            server2 = BatcherIpcServer(sock_path, batcher2, transport="shm")
            server2.start()
            try:
                assert wait_for(client._connected.is_set)
                assert client.transport == "shm"
                assert client._shm is not first_seg
                inputs = [inp(i) for i in range(8)]
                assert effects(client.check(inputs)) == effects(oracle(rt, inputs))
            finally:
                client.close()
                server2.close()
                batcher2.close()
        finally:
            client.close()
