"""The reference's cel_eval golden cases through the CEL runtime.

Behavioral reference: internal/engine/evaluator_test.go TestSatisfiesCondition:
each case compiles a condition tree and evaluates it against the request with
now() pinned to 2021-04-22T10:05:20.021-05:00, comparing the boolean result.
"""

import datetime

import pytest

from cerbos_tpu.compile.compiler import _Ctx, _compile_match
from cerbos_tpu.engine.types import EvalParams
from cerbos_tpu.policy import model
from cerbos_tpu.ruletable.check import EvalContext, build_request_messages
from cerbos_tpu.cel.values import Timestamp

from golden_loader import load_cases, parse_input

CASES = load_cases("cel_eval")

NOW = Timestamp.from_datetime(
    datetime.datetime(2021, 4, 22, 10, 5, 20, 21000,
                      tzinfo=datetime.timezone(datetime.timedelta(hours=-5)))
)


def parse_match(raw: dict) -> model.Match:
    if "expr" in raw:
        return model.Match(expr=raw["expr"])
    for kind in ("all", "any", "none"):
        if kind in raw:
            children = [parse_match(m) for m in raw[kind].get("of", [])]
            return model.Match(**{kind: children})
    raise ValueError(f"unrecognized condition node: {raw}")


def _id(case_tuple):
    return case_tuple[0].rsplit("/", 1)[-1]


@pytest.mark.parametrize("case_tuple", CASES, ids=_id)
def test_cel_eval(case_tuple):
    name, case = case_tuple
    dummy = model.Policy()
    dummy.source_file = name
    ctx = _Ctx({}, dummy)
    cond = _compile_match(parse_match(case["condition"]), ctx, ("condition",))
    assert not ctx.details, [d.render() for d in ctx.details]

    inp = parse_input(case["request"])
    request, principal, resource = build_request_messages(inp)
    params = EvalParams(now_fn=lambda: NOW)
    ec = EvalContext(params, request, principal, resource)
    have = ec.satisfies_condition(cond, {}, {})
    assert have == case["want"], f"{name}: want {case['want']} have {have}"
