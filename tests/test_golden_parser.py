"""Reference parser corpus: strict YAML/JSON unmarshalling with positions.

Mirrors internal/parser/parser_test.go TestUnmarshal: each case_NNN.json is a
ProtoYamlTestCase (description, wantErrors, want[{message, errors}]) and the
.input file is the YAML/JSON stream. Errors compare as structured values
(kind, position{line, column, path}, message) after the reference's own
sort (line desc, then column desc); messages compare as protojson dicts.
"""

import json
import os

import pytest

from cerbos_tpu.policy import protoschema as S
from cerbos_tpu.policy.protoyaml import unmarshal

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "parser")

CASES = sorted(f for f in os.listdir(CORPUS) if f.endswith(".json"))


def _norm_errors(errs):
    out = []
    for e in errs:
        d = {
            "kind": e["kind"] if isinstance(e, dict) else e.kind,
        }
        if isinstance(e, dict):
            pos = e.get("position")
            msg = e.get("message", "")
        else:
            pos = {"line": e.line, "column": e.column, "path": e.path} if e.line else None
            msg = e.message
        if pos:
            d["position"] = {
                "line": pos.get("line", 0),
                "column": pos.get("column", 0),
                "path": pos.get("path", ""),
            }
        d["message"] = msg
        out.append(d)
    out.sort(key=lambda d: (-d.get("position", {}).get("line", 0), -d.get("position", {}).get("column", 0), d["message"]))
    return out


def _norm_msg(v):
    if isinstance(v, dict):
        return {k: _norm_msg(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm_msg(x) for x in v]
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


@pytest.mark.parametrize("case", CASES)
def test_parser_case(case):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = json.load(f)
    with open(os.path.join(CORPUS, case + ".input"), "rb") as f:
        data = f.read()

    res = unmarshal(data, S.POLICY)

    want_errors = tc.get("wantErrors") or []
    if want_errors:
        assert res.errors, f"{case}: expected errors, got none"
        assert _norm_errors(want_errors) == _norm_errors(res.errors), case
    else:
        assert not res.errors, f"{case}: unexpected errors: {[e.render() for e in res.errors]}"

    want_docs = tc.get("want") or []
    assert len(res.docs) == len(want_docs), (
        f"{case}: want {len(want_docs)} docs, got {len(res.docs)}"
    )
    for i, want in enumerate(want_docs):
        have = res.docs[i]
        assert _norm_msg(want.get("message") or {}) == _norm_msg(have.message), f"{case} doc {i}"
        if want.get("errors"):
            assert _norm_errors(want["errors"]) == _norm_errors(have.errors), f"{case} doc {i} errors"
