"""Distinct-condition scale: kernel templating keeps the device graph small.

VERDICT r2 weak #4: per-policy distinct conditions must not explode the jit
graph. Kernels identical up to literals share one template; the traced
subgraph count is O(templates), not O(conditions) (docs/PERF.md records the
full-scale numbers: 2,000 kernels → 126 s XLA compile untemplated, seconds
templated).
"""

import numpy as np
import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu import TpuEvaluator


def distinct_condition_corpus(n: int) -> str:
    docs = []
    for i in range(n):
        docs.append(f"""
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: res{i}
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.amount < {i * 7 + 3}
    - actions: ["edit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.tier == "tier{i}" && R.attr.level >= {i % 97}
""")
    return "\n---\n".join(docs)


def scale_inputs(n_policies: int, count: int, seed: int = 0) -> list[CheckInput]:
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(count):
        i = int(rng.integers(0, n_policies))
        out.append(CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind=f"res{i}", id="x", attr={
                "amount": float(rng.integers(0, 20000)),
                "tier": f"tier{int(rng.integers(0, n_policies))}",
                "level": float(rng.integers(0, 100)),
            }),
            actions=["view", "edit"],
        ))
    return out


N = 50  # 100 distinct condition kernels


@pytest.fixture(scope="module")
def scale_table():
    return build_rule_table(compile_policy_set(list(parse_policies(distinct_condition_corpus(N)))))


def test_kernels_group_into_templates(scale_table):
    ev = TpuEvaluator(scale_table, use_jax=False, min_device_batch=0)
    compiler = ev.lowered.compiler
    assert len(compiler.kernels) == 2 * N
    compiler.build_groups()
    # two rule shapes → two templates, regardless of policy count
    assert len(compiler.groups) == 2
    assert sorted(cid for g in compiler.groups for cid in g.cond_ids) == list(range(2 * N))


@pytest.mark.parametrize("use_jax", [False, True])
def test_scale_corpus_parity(scale_table, use_jax):
    ev = TpuEvaluator(scale_table, use_jax=use_jax, min_device_batch=0)
    params = EvalParams()
    inputs = scale_inputs(N, 256)
    got = ev.check(inputs, params)
    assert ev.stats["oracle_inputs"] == 0, "scale corpus must be fully device-served"
    for inp, g in zip(inputs, got):
        w = check_input(scale_table, inp, params)
        assert {a: (e.effect, e.policy) for a, e in g.actions.items()} == {
            a: (e.effect, e.policy) for a, e in w.actions.items()
        }


N_BIG = 5_000  # 10,000 distinct condition kernels


@pytest.fixture(scope="module")
def big_scale_table():
    return build_rule_table(
        compile_policy_set(list(parse_policies(distinct_condition_corpus(N_BIG))))
    )


def _steady_seconds(ev, inputs, params, iters=5) -> float:
    import time

    ev.check(inputs, params)  # warm: jit trace / caches
    ev.check(inputs, params)
    best = float("inf")
    for _ in range(iters):
        t0 = time.process_time()
        ev.check(inputs, params)
        best = min(best, time.process_time() - t0)
    return best


@pytest.mark.parametrize("use_jax", [False, True])
def test_10k_kernel_steady_state_within_2x(scale_table, big_scale_table, use_jax):
    """VERDICT r3 item 2: a batch referencing a sparse slice of a 10k-kernel
    table must run within 2x of the same batch against a 100-kernel table —
    on BOTH backends. The group-member variants make sat (and the jit trace)
    O(active conditions), so table size stops being a per-batch cost."""
    params = EvalParams()
    # same request slice (kinds 0..N-1) against both tables
    inputs = scale_inputs(N, 512)

    ev_small = TpuEvaluator(scale_table, use_jax=use_jax, min_device_batch=0)
    ev_big = TpuEvaluator(big_scale_table, use_jax=use_jax, min_device_batch=0)

    # parity first: the big table must decide the slice identically
    got = ev_big.check(inputs, params)
    assert ev_big.stats["oracle_inputs"] == 0
    for inp, g in zip(inputs, got):
        w = check_input(big_scale_table, inp, params)
        assert {a: (e.effect, e.policy) for a, e in g.actions.items()} == {
            a: (e.effect, e.policy) for a, e in w.actions.items()
        }

    t_small = _steady_seconds(ev_small, inputs, params)
    t_big = _steady_seconds(ev_big, inputs, params)
    assert t_big <= 2.0 * t_small + 0.005, (
        f"10k-kernel steady state {t_big * 1e3:.1f}ms vs "
        f"100-kernel {t_small * 1e3:.1f}ms exceeds 2x"
    )
