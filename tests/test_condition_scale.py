"""Distinct-condition scale: kernel templating keeps the device graph small.

VERDICT r2 weak #4: per-policy distinct conditions must not explode the jit
graph. Kernels identical up to literals share one template; the traced
subgraph count is O(templates), not O(conditions) (docs/PERF.md records the
full-scale numbers: 2,000 kernels → 126 s XLA compile untemplated, seconds
templated).
"""

import numpy as np
import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu import TpuEvaluator


def distinct_condition_corpus(n: int) -> str:
    docs = []
    for i in range(n):
        docs.append(f"""
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: res{i}
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.amount < {i * 7 + 3}
    - actions: ["edit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.tier == "tier{i}" && R.attr.level >= {i % 97}
""")
    return "\n---\n".join(docs)


def scale_inputs(n_policies: int, count: int, seed: int = 0) -> list[CheckInput]:
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(count):
        i = int(rng.integers(0, n_policies))
        out.append(CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind=f"res{i}", id="x", attr={
                "amount": float(rng.integers(0, 20000)),
                "tier": f"tier{int(rng.integers(0, n_policies))}",
                "level": float(rng.integers(0, 100)),
            }),
            actions=["view", "edit"],
        ))
    return out


N = 50  # 100 distinct condition kernels


@pytest.fixture(scope="module")
def scale_table():
    return build_rule_table(compile_policy_set(list(parse_policies(distinct_condition_corpus(N)))))


def test_kernels_group_into_templates(scale_table):
    ev = TpuEvaluator(scale_table, use_jax=False, min_device_batch=0)
    compiler = ev.lowered.compiler
    assert len(compiler.kernels) == 2 * N
    compiler.build_groups()
    # two rule shapes → two templates, regardless of policy count
    assert len(compiler.groups) == 2
    assert sorted(cid for g in compiler.groups for cid in g.cond_ids) == list(range(2 * N))


@pytest.mark.parametrize("use_jax", [False, True])
def test_scale_corpus_parity(scale_table, use_jax):
    ev = TpuEvaluator(scale_table, use_jax=use_jax, min_device_batch=0)
    params = EvalParams()
    inputs = scale_inputs(N, 256)
    got = ev.check(inputs, params)
    assert ev.stats["oracle_inputs"] == 0, "scale corpus must be fully device-served"
    for inp, g in zip(inputs, got):
        w = check_input(scale_table, inp, params)
        assert {a: (e.effect, e.policy) for a, e in g.actions.items()} == {
            a: (e.effect, e.policy) for a, e in w.actions.items()
        }
