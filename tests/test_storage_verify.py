"""Storage drivers, reload pipeline, schema validation, auxdata, verify framework."""

import base64
import json
import os
import time

import pytest
import yaml

from cerbos_tpu.auxdata import AuxDataManager, JWTError, KeySet
from cerbos_tpu.engine import CheckInput, Engine, Principal, Resource
from cerbos_tpu.ruletable.manager import RuleTableManager
from cerbos_tpu.schema import SchemaManager
from cerbos_tpu.storage import DiskStore, OverlayStore, SqliteStore
from cerbos_tpu.verify.runner import discover_and_run

POLICY_A = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""

POLICY_B = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view", "edit"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


def write(p, name, content):
    (p / name).write_text(content)


class TestDiskStore:
    def test_load_and_events(self, tmp_path):
        write(tmp_path, "a.yaml", POLICY_A)
        store = DiskStore(str(tmp_path))
        assert len(store.get_all()) == 1

        events = []
        store.subscribe(lambda evs: events.extend(evs))
        time.sleep(0.02)
        write(tmp_path, "a.yaml", POLICY_B)
        os.utime(tmp_path / "a.yaml", (time.time() + 5, time.time() + 5))
        store.check_for_changes()
        assert events and events[0].kind == "ADD_OR_UPDATE"

        (tmp_path / "a.yaml").unlink()
        store.check_for_changes()
        assert events[-1].kind == "DELETE"
        store.close()

    def test_reload_pipeline(self, tmp_path):
        write(tmp_path, "a.yaml", POLICY_A)
        store = DiskStore(str(tmp_path))
        mgr = RuleTableManager(store)
        eng = Engine(mgr.rule_table)

        def check():
            return eng.check([CheckInput(principal=Principal(id="u", roles=["user"]), resource=Resource(kind="doc", id="d"), actions=["edit"])])[0]

        # manager swaps tables; engine follows via on_swap
        mgr.on_swap = lambda rt: setattr(eng, "rule_table", rt)
        assert check().actions["edit"].effect == "EFFECT_DENY"
        write(tmp_path, "a.yaml", POLICY_B)
        os.utime(tmp_path / "a.yaml", (time.time() + 5, time.time() + 5))
        store.check_for_changes()
        assert check().actions["edit"].effect == "EFFECT_ALLOW"
        store.close()

    def test_bad_policy_keeps_last_state(self, tmp_path):
        write(tmp_path, "a.yaml", POLICY_A)
        store = DiskStore(str(tmp_path))
        mgr = RuleTableManager(store)
        before = mgr.rule_table
        write(tmp_path, "b.yaml", "apiVersion: api.cerbos.dev/v1\nresourcePolicy:\n  resource: [broken\n")
        os.utime(tmp_path / "b.yaml", (time.time() + 5, time.time() + 5))
        store.check_for_changes()
        # invalid file is ignored; table still serves
        assert mgr.rule_table is not None
        store.close()


class TestSqliteStore:
    def test_crud_and_events(self):
        store = SqliteStore(":memory:")
        events = []
        store.subscribe(lambda evs: events.extend(evs))
        fqns = store.add_or_update([POLICY_A])
        assert fqns == ["cerbos.resource.doc.vdefault"]
        assert len(store.get_all()) == 1
        assert store.get_raw(fqns[0]) is not None

        store.set_disabled(fqns, True)
        assert store.get_all() == []
        store.set_disabled(fqns, False)
        assert len(store.get_all()) == 1

        store.add_schema("doc.json", b'{"type": "object"}')
        assert store.get_schema("doc.json") == b'{"type": "object"}'
        assert store.list_schema_ids() == ["doc.json"]
        assert store.delete_schema("doc.json")

        store.delete(fqns)
        assert store.get_all() == []
        assert any(e.kind == "DELETE" for e in events)
        store.close()


class TestOverlay:
    def test_failover(self, tmp_path):
        base_dir, fb_dir = tmp_path / "base", tmp_path / "fb"
        base_dir.mkdir(), fb_dir.mkdir()
        write(base_dir, "a.yaml", POLICY_A)
        write(fb_dir, "a.yaml", POLICY_B)
        base, fb = DiskStore(str(base_dir)), DiskStore(str(fb_dir))
        ov = OverlayStore(base, fb, failure_threshold=1, cooldown_s=60)
        assert len(ov.get_all()) == 1

        def boom():
            raise RuntimeError("base down")

        base.get_all = boom  # type: ignore[assignment]
        # first failure trips the breaker and falls back
        pols = ov.get_all()
        assert pols[0].resource_policy.rules[0].actions == ["view", "edit"]
        ov.close()


class TestSchemaValidation:
    SCHEMA = {"type": "object", "properties": {"owner": {"type": "string"}}, "required": ["owner"]}

    def make(self, tmp_path, enforcement):
        write(tmp_path, "doc.yaml", """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  schemas:
    resourceSchema:
      ref: cerbos:///doc.json
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
""")
        schemas_dir = tmp_path / "_schemas"
        schemas_dir.mkdir()
        (schemas_dir / "doc.json").write_text(json.dumps(self.SCHEMA))
        store = DiskStore(str(tmp_path))
        mgr = RuleTableManager(store)
        schema_mgr = SchemaManager(store, enforcement=enforcement)
        return Engine(mgr.rule_table, schema_mgr=schema_mgr), store

    def test_warn_allows_with_errors(self, tmp_path):
        eng, store = self.make(tmp_path, "warn")
        out = eng.check([CheckInput(principal=Principal(id="u", roles=["user"]), resource=Resource(kind="doc", id="d", attr={}), actions=["view"])])[0]
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        assert out.validation_errors and out.validation_errors[0].source == "SOURCE_RESOURCE"
        store.close()

    def test_reject_denies(self, tmp_path):
        eng, store = self.make(tmp_path, "reject")
        out = eng.check([CheckInput(principal=Principal(id="u", roles=["user"]), resource=Resource(kind="doc", id="d", attr={}), actions=["view"])])[0]
        assert out.actions["view"].effect == "EFFECT_DENY"
        ok = eng.check([CheckInput(principal=Principal(id="u", roles=["user"]), resource=Resource(kind="doc", id="d", attr={"owner": "u"}), actions=["view"])])[0]
        assert ok.actions["view"].effect == "EFFECT_ALLOW"
        store.close()


class TestAuxData:
    def test_hmac_jwt_roundtrip(self):
        import hashlib
        import hmac as hmac_mod

        secret = b"supersecretkey"
        header = base64.urlsafe_b64encode(json.dumps({"alg": "HS256", "typ": "JWT"}).encode()).rstrip(b"=")
        payload = base64.urlsafe_b64encode(
            json.dumps({"iss": "test", "aud": ["cerbos-jwt-tests"], "exp": time.time() + 3600}).encode()
        ).rstrip(b"=")
        sig = base64.urlsafe_b64encode(
            hmac_mod.new(secret, header + b"." + payload, hashlib.sha256).digest()
        ).rstrip(b"=")
        token = b".".join([header, payload, sig]).decode()

        mgr = AuxDataManager([KeySet(id="default", keys=[("hmac", secret)])])
        aux = mgr.extract(token)
        assert aux.jwt["iss"] == "test"

        with pytest.raises(JWTError):
            mgr.extract(token[:-2] + "xx")

    def test_expired_jwt(self):
        secret = b"k"
        import hashlib
        import hmac as hmac_mod

        header = base64.urlsafe_b64encode(json.dumps({"alg": "HS256"}).encode()).rstrip(b"=")
        payload = base64.urlsafe_b64encode(json.dumps({"exp": time.time() - 10}).encode()).rstrip(b"=")
        sig = base64.urlsafe_b64encode(hmac_mod.new(secret, header + b"." + payload, hashlib.sha256).digest()).rstrip(b"=")
        token = b".".join([header, payload, sig]).decode()
        mgr = AuxDataManager([KeySet(id="default", keys=[("hmac", secret)])])
        with pytest.raises(JWTError):
            mgr.extract(token)


class TestVerifyFramework:
    def test_suite_run(self, tmp_path):
        write(tmp_path, "doc.yaml", POLICY_B)
        testdata = tmp_path / "testdata"
        testdata.mkdir()
        (testdata / "principals.yaml").write_text(yaml.safe_dump({
            "principals": {"u1": {"id": "u1", "roles": ["user"]}, "ghost": {"id": "g", "roles": ["nobody"]}}
        }))
        (testdata / "resources.yaml").write_text(yaml.safe_dump({
            "resources": {"d1": {"kind": "doc", "id": "d1"}}
        }))
        write(tmp_path, "doc_test.yaml", yaml.safe_dump({
            "name": "DocSuite",
            "tests": [{
                "name": "user access",
                "input": {"principals": ["u1", "ghost"], "resources": ["d1"], "actions": ["view", "edit", "delete"]},
                "expected": [
                    {"principal": "u1", "resource": "d1",
                     "actions": {"view": "EFFECT_ALLOW", "edit": "EFFECT_ALLOW", "delete": "EFFECT_DENY"}},
                ],
            }],
        }))
        results = discover_and_run(str(tmp_path))
        assert results is not None
        assert not results.failed
        suite = results.results["suites"][0]
        tc = suite["testCases"][0]
        assert len(tc["principals"]) == 2  # 2 principals x 1 resource
        assert results.results["summary"]["testsCount"] == 6  # x 3 actions

    def test_failing_expectation(self, tmp_path):
        write(tmp_path, "doc.yaml", POLICY_A)
        write(tmp_path, "doc_test.yaml", yaml.safe_dump({
            "name": "Failing",
            "tests": [{
                "name": "wrong expectation",
                "input": {"principals": ["u1"], "resources": ["d1"], "actions": ["view"]},
                "expected": [{"principal": "u1", "resource": "d1", "actions": {"view": "EFFECT_DENY"}}],
            }],
            "principals": {"u1": {"id": "u1", "roles": ["user"]}},
            "resources": {"d1": {"kind": "doc", "id": "d1"}},
        }))
        results = discover_and_run(str(tmp_path))
        assert results.failed
        details = (
            results.results["suites"][0]["testCases"][0]["principals"][0]["resources"][0]
            ["actions"][0]["details"]
        )
        assert details["result"] == "RESULT_FAILED"
        assert details["failure"] == {"expected": "EFFECT_DENY", "actual": "EFFECT_ALLOW"}
        assert "expected EFFECT_DENY, got EFFECT_ALLOW" in results.summary()
        assert "testsuite" in results.to_junit()


class TestDBDialects:
    """The dialect-parameterized core (internal/storage/db analogue): the
    shared store logic runs against sqlite; the mysql/postgres dialects carry
    their SQL and fail with a clear error when no driver is installed."""

    def test_core_roundtrip_via_dialect(self):
        from cerbos_tpu.storage.db import DBStore, Sqlite3Dialect

        store = DBStore(Sqlite3Dialect(), {"dsn": ":memory:"})
        fqns = store.add_or_update([POLICY_A])
        assert fqns == ["cerbos.resource.doc.vdefault"]
        assert store.list_policy_ids() == fqns
        assert store.get(fqns[0]) is not None
        store.add_schema("s.json", b"{}")
        assert store.get_schema("s.json") == b"{}"
        assert store.set_disabled(fqns, True) == 1
        assert store.list_policy_ids() == []
        assert store.list_policy_ids(include_disabled=True) == fqns
        assert store.delete_schema("s.json")
        store.close()

    def test_dialect_sql_differences(self):
        from cerbos_tpu.storage.db import MySQLDialect, PostgresDialect, Sqlite3Dialect

        assert "ON CONFLICT(fqn)" in Sqlite3Dialect().upsert_policy()
        assert "ON DUPLICATE KEY UPDATE" in MySQLDialect().upsert_policy()
        assert "ON CONFLICT(fqn)" in PostgresDialect().upsert_policy()
        assert MySQLDialect().placeholder == "%s"
        # every dialect creates the same two tables
        for d in (Sqlite3Dialect(), MySQLDialect(), PostgresDialect()):
            ddl = " ".join(d.ddl())
            assert "policy" in ddl and "schema_defs" in ddl

    def test_missing_driver_errors(self):
        from cerbos_tpu.storage import new_store

        for driver in ("mysql", "postgres"):
            with pytest.raises(RuntimeError, match="requires"):
                new_store({"driver": driver, driver: {}})


class TestKafkaAuditBackend:
    """Partitioning/encoding semantics (internal/audit/kafka/publisher.go)
    unit-tested through an injected producer."""

    def _entry(self, call_id="01HCALL", kind="decision"):
        return {"callId": call_id, "kind": kind, "timestamp": "2026-01-01T00:00:00Z",
                "checkResources": {"inputs": []}}

    def test_headers_key_and_encoding(self):
        from cerbos_tpu.audit import InMemoryTransport, KafkaBackend

        producer = InMemoryTransport()
        backend = KafkaBackend(topic="cerbos.audit.log", producer=producer)
        backend.write(self._entry(kind="decision"))
        backend.write(self._entry(call_id="01HOTHER", kind="access"))
        backend.close()

        assert len(producer.records) == 2
        dec, acc = producer.records
        assert dec.topic == "cerbos.audit.log"
        assert dec.key == b"01HCALL"  # partition key = call id
        assert dict(dec.headers)["cerbos.audit.kind"] == b"decision"
        assert dict(acc.headers)["cerbos.audit.kind"] == b"access"
        assert dict(dec.headers)["cerbos.audit.encoding"] == b"json"
        assert json.loads(dec.value)["callId"] == "01HCALL"

    def test_same_call_same_partition_key(self):
        from cerbos_tpu.audit import InMemoryTransport, KafkaBackend

        producer = InMemoryTransport()
        backend = KafkaBackend(topic="t", producer=producer)
        backend.write(self._entry(call_id="X", kind="access"))
        backend.write(self._entry(call_id="X", kind="decision"))
        assert producer.records[0].key == producer.records[1].key

    def test_invalid_config(self):
        from cerbos_tpu.audit import InMemoryTransport, KafkaBackend

        with pytest.raises(ValueError, match="invalid topic"):
            KafkaBackend(topic="", producer=InMemoryTransport())
        with pytest.raises(ValueError, match="invalid encoding"):
            KafkaBackend(topic="t", producer=InMemoryTransport(), encoding="xml")

    def test_error_callback(self):
        from cerbos_tpu.audit import KafkaBackend

        class Failing:
            def produce(self, record):
                raise ConnectionError("broker down")

        seen = []
        backend = KafkaBackend(topic="t", producer=Failing(), on_error=lambda e, r: seen.append((e, r)))
        backend.write(self._entry())
        assert len(seen) == 1 and isinstance(seen[0][0], ConnectionError)

    def test_file_transport_end_to_end(self, tmp_path):
        from cerbos_tpu.audit import new_audit_log

        out = tmp_path / "kafka.jsonl"
        log = new_audit_log({
            "enabled": True, "accessLogsEnabled": True, "decisionLogsEnabled": True,
            "backend": "kafka", "kafka": {"topic": "cerbos.audit.log", "file": str(out)},
        })
        log.write_access("01HCALL", method="/cerbos.svc.v1.CerbosService/CheckResources")
        log.close()
        lines = [json.loads(l) for l in out.read_text().splitlines()]
        assert lines and lines[0]["topic"] == "cerbos.audit.log"
        assert lines[0]["headers"]["cerbos.audit.kind"] == "access"
        assert lines[0]["key"] == "01HCALL"
