from cerbos_tpu import globs, namer


def test_scope_parents():
    assert list(namer.scope_parents("a.b.c")) == ["a.b", "a", ""]
    assert list(namer.scope_parents("a")) == [""]
    assert list(namer.scope_parents("")) == []
    assert namer.scope_chain("a.b") == ["a.b", "a", ""]
    assert namer.scope_chain("") == [""]


def test_fqns():
    assert namer.resource_policy_fqn("leave_request", "default") == "cerbos.resource.leave_request.vdefault"
    assert (
        namer.resource_policy_fqn("leave_request", "20210210", "acme.hr")
        == "cerbos.resource.leave_request.v20210210/acme.hr"
    )
    assert namer.principal_policy_fqn("daffy_duck", "dev") == "cerbos.principal.daffy_duck.vdev"
    assert namer.role_policy_fqn("acme_admin", "", "acme") == "cerbos.role.acme_admin.vdefault/acme"
    assert namer.derived_roles_fqn("apatr_common_roles") == "cerbos.derived_roles.apatr_common_roles"
    assert namer.policy_key_from_fqn("cerbos.resource.x.vdefault") == "resource.x.vdefault"


def test_sanitize():
    assert namer.sanitize("a:b/c") == "a_b_c"
    # names not matching the legacy pattern pass through untouched
    assert namer.sanitize("ns::res") == "ns::res"


def test_glob_separator_semantics():
    assert globs.matches_glob("view:*", "view:public")
    assert not globs.matches_glob("view:*", "view:public:extra")
    assert globs.matches_glob("view:**", "view:public:extra")
    # bare * is promoted to ** (matches everything)
    assert globs.matches_glob("*", "anything:at:all")
    assert globs.matches_glob("a?c", "abc")
    assert not globs.matches_glob("a?c", "a:c")
    assert globs.matches_glob("{view,edit}:*", "edit:doc")
    assert not globs.matches_glob("{view,edit}:*", "delete:doc")
    assert globs.matches_glob("[vV]iew", "View")
    assert not globs.matches_glob("[!v]iew", "view")


def test_is_glob():
    assert globs.is_glob("view:*")
    assert not globs.is_glob("view:public")
    assert not globs.is_glob("view\\*")
    assert globs.is_glob("{a,b}")
