"""The reference's own golden engine cases, run through the CPU oracle.

Behavioral reference: internal/engine/engine_test.go TestCheck (engine +
engine_strict_scope_search under strict scope search),
TestCheckWithLenientScopeSearch (engine + engine_lenient_scope_search),
TestSchemaValidation (engine_schema_enforcement/{warn,reject}).
"""

import pytest

from golden_loader import golden_engine, load_cases, run_case

STRICT_CASES = load_cases("engine") + load_cases("engine_strict_scope_search")
LENIENT_CASES = load_cases("engine") + load_cases("engine_lenient_scope_search")
WARN_CASES = load_cases("engine_schema_enforcement/warn")
REJECT_CASES = load_cases("engine_schema_enforcement/reject")


def _id(case_tuple):
    name, case = case_tuple
    return f"{name}:{case.get('description', '')[:40]}"


@pytest.fixture(scope="module")
def strict_engine():
    return golden_engine(lenient=False)


@pytest.fixture(scope="module")
def lenient_engine():
    return golden_engine(lenient=True)


@pytest.fixture(scope="module")
def warn_engine():
    return golden_engine(schema_enforcement="warn")


@pytest.fixture(scope="module")
def reject_engine():
    return golden_engine(schema_enforcement="reject")


@pytest.mark.parametrize("case_tuple", STRICT_CASES, ids=_id)
def test_strict(strict_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(strict_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", LENIENT_CASES, ids=_id)
def test_lenient(lenient_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(lenient_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", WARN_CASES, ids=_id)
def test_schema_warn(warn_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(warn_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", REJECT_CASES, ids=_id)
def test_schema_reject(reject_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(reject_engine, case)
    assert not errs, "\n".join(errs)
