"""The reference's own golden engine cases, run through the CPU oracle.

Behavioral reference: internal/engine/engine_test.go TestCheck (engine +
engine_strict_scope_search under strict scope search),
TestCheckWithLenientScopeSearch (engine + engine_lenient_scope_search),
TestSchemaValidation (engine_schema_enforcement/{warn,reject}).
"""

import json

import pytest

from cerbos_tpu.engine import Engine, EvalParams

from golden_loader import GOLDEN_GLOBALS, golden_engine, load_cases, run_case

STRICT_CASES = load_cases("engine") + load_cases("engine_strict_scope_search")
LENIENT_CASES = load_cases("engine") + load_cases("engine_lenient_scope_search")
WARN_CASES = load_cases("engine_schema_enforcement/warn")
REJECT_CASES = load_cases("engine_schema_enforcement/reject")


def _id(case_tuple):
    name, case = case_tuple
    return f"{name}:{case.get('description', '')[:40]}"


@pytest.fixture(scope="module")
def strict_engine():
    return golden_engine(lenient=False)


@pytest.fixture(scope="module")
def lenient_engine():
    return golden_engine(lenient=True)


@pytest.fixture(scope="module")
def warn_engine():
    return golden_engine(schema_enforcement="warn")


@pytest.fixture(scope="module")
def reject_engine():
    return golden_engine(schema_enforcement="reject")


@pytest.fixture(scope="module", params=["numpy", "jax", "mesh8"])
def device_engine(request):
    """The same golden cases through the TPU evaluator (device path): numpy
    fallback, single-device jax, and jax sharded over the 8-device CPU mesh —
    gating device ≡ reference."""
    from cerbos_tpu.ruletable import build_rule_table
    from cerbos_tpu.tpu import TpuEvaluator
    from golden_loader import golden_policies

    mesh = None
    if request.param == "mesh8":
        from cerbos_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(8)
    _, compiled = golden_policies()
    table = build_rule_table(compiled)
    ev = TpuEvaluator(
        table,
        globals_=dict(GOLDEN_GLOBALS),
        use_jax=request.param != "numpy",
        min_device_batch=0,
        mesh=mesh,
    )
    return Engine(
        table,
        eval_params=EvalParams(globals=dict(GOLDEN_GLOBALS)),
        tpu_evaluator=ev,
        tpu_batch_threshold=1,
    )


@pytest.mark.parametrize("case_tuple", STRICT_CASES, ids=_id)
def test_strict_device(device_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(device_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", LENIENT_CASES, ids=_id)
def test_lenient_device(device_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(
        device_engine,
        case,
        params=EvalParams(globals=dict(GOLDEN_GLOBALS), lenient_scope_search=True),
    )
    assert not errs, "\n".join(errs)


@pytest.fixture(scope="module", params=["warn", "reject"])
def device_schema_engine(request):
    """Schema-enforcement golden cases through the device path."""
    from cerbos_tpu.ruletable import build_rule_table
    from cerbos_tpu.schema import SchemaManager
    from cerbos_tpu.tpu import TpuEvaluator
    from golden_loader import golden_policies

    store, compiled = golden_policies()
    table = build_rule_table(compiled)
    schema_mgr = SchemaManager(store, enforcement=request.param)
    ev = TpuEvaluator(
        table,
        globals_=dict(GOLDEN_GLOBALS),
        schema_mgr=schema_mgr,
        use_jax=False,
        min_device_batch=0,
    )
    engine = Engine(
        table,
        schema_mgr=schema_mgr,
        eval_params=EvalParams(globals=dict(GOLDEN_GLOBALS)),
        tpu_evaluator=ev,
        tpu_batch_threshold=1,
    )
    return request.param, engine


def test_schema_device(device_schema_engine):
    enforcement, engine = device_schema_engine
    cases = WARN_CASES if enforcement == "warn" else REJECT_CASES
    for name, case in cases:
        errs = run_case(engine, case)
        assert not errs, f"{name}: " + "\n".join(errs)


@pytest.mark.parametrize("case_tuple", STRICT_CASES, ids=_id)
def test_strict(strict_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(strict_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", LENIENT_CASES, ids=_id)
def test_lenient(lenient_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(lenient_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", WARN_CASES, ids=_id)
def test_schema_warn(warn_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(warn_engine, case)
    assert not errs, "\n".join(errs)


@pytest.mark.parametrize("case_tuple", REJECT_CASES, ids=_id)
def test_schema_reject(reject_engine, case_tuple):
    _, case = case_tuple
    errs = run_case(reject_engine, case)
    assert not errs, "\n".join(errs)


class TestGoldenDecisionLogs:
    """wantDecisionLogs from the golden engine cases, through the real audit
    pipeline (async writer + backend). Compared per engine_test.go:100-112:
    callId/timestamp/peer ignored, effectiveDerivedRoles and roles order-
    insensitive, empty fields omitted. policySource (a store-driver marker
    rewritten by the reference harness) is not modeled in entries here."""

    def _norm(self, v, sort_keys=(), top=True):
        from golden_loader import _norm_val

        if isinstance(v, dict):
            out = {}
            for k, x in v.items():
                skip = ("callId", "timestamp", "peer", "policySource")
                # "kind" is the entry discriminator only at the TOP level;
                # nested kinds (resource.kind) must compare. "provenance" is
                # this PDP's extension block (winning rule + evaluator per
                # action, audit/log.py) — upstream fixtures don't carry it
                if k in skip or (top and k in ("kind", "provenance")):
                    continue
                n = self._norm(x, sort_keys, top=False)
                if k in ("effectiveDerivedRoles", "effective_derived_roles", "roles"):
                    n = sorted(n, key=str)
                    k = "effectiveDerivedRoles" if k.startswith("effective") else k
                if k == "outputs" and isinstance(n, list) and n and isinstance(n[0], dict) and "src" in n[0]:
                    n = sorted(n, key=lambda o: o.get("src", ""))
                if n in ("", [], {}, None):
                    continue
                out[k] = n
            return out
        if isinstance(v, list):
            return [self._norm(x, sort_keys, top=False) for x in v]
        return _norm_val(v)

    @pytest.mark.parametrize(
        "case_tuple",
        [c for c in STRICT_CASES if c[1].get("wantDecisionLogs")],
        ids=_id,
    )
    def test_decision_logs(self, strict_engine, case_tuple):
        from cerbos_tpu.audit.log import AuditLog

        from golden_loader import parse_input

        _, case = case_tuple

        class Capture:
            def __init__(self):
                self.entries = []

            def write(self, entry):
                self.entries.append(entry)

        backend = Capture()
        log = AuditLog(backend=backend)
        inputs = [parse_input(raw) for raw in case.get("inputs", [])]
        outputs = strict_engine.check(inputs)
        log.write_decision("test-call", inputs, outputs)
        log.close()

        assert len(backend.entries) == 1
        have = self._norm(backend.entries[0])
        want_logs = case["wantDecisionLogs"]
        assert len(want_logs) == 1
        want = self._norm(want_logs[0])
        assert have == want, f"\nwant {json.dumps(want, sort_keys=True, indent=1)}\nhave {json.dumps(have, sort_keys=True, indent=1)}"
