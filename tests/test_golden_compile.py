"""Reference compile corpus: structured compile errors with positions.

Mirrors internal/compile/compile_test.go TestCompile: each case_NNN.yaml is a
CompileTestCase descriptor (mainDef, wantErrors, wantVariables), the .input
is a txtar archive of the compilation unit. Errors compare on (file, error,
position{line, column, path}) plus exact description text — except CEL and
JSON-schema diagnostics, whose bracketed tool output differs from cel-go /
santhosh-tekuri byte-wise (compared by prefix; recorded in
tests/golden/UNSUPPORTED.md).

Golden-ok cases assert clean compilation; where the descriptor carries
wantVariables, the per-scope USED constant/variable sets (and per derived
role) are compared against the reference's.
"""

import json
import os

import pytest
import yaml

from cerbos_tpu.compile.compiler import (
    CompileError,
    _constant_refs,
    _variable_refs,
    compile_policy,
)
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.storage.disk import DiskStore
from test_golden_verify import expand_txtar

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "compile")
SCHEMA_FS = os.path.join(os.path.dirname(__file__), "golden", "schema_fs")

CASES = sorted(
    f for f in os.listdir(CORPUS)
    if f.endswith(".yaml") and os.path.exists(os.path.join(CORPUS, f + ".input"))
)

# descriptions whose tails embed third-party diagnostic text: compare prefix
_PREFIX_KINDS = {"invalid expression"}


def _schema_check(ref: str):
    """Compile-time schema probe over the schema_fs store (mkSchemaMgr)."""
    store = DiskStore(SCHEMA_FS)
    schema_id = ref[len("cerbos:///"):] if ref.startswith("cerbos:///") else ref
    raw = store.get_schema(schema_id)
    if raw is None:
        return ("missing", f"_schemas/{schema_id}")
    try:
        import jsonschema

        jsonschema.Draft202012Validator.check_schema(json.loads(raw))
        jsonschema.Draft202012Validator(json.loads(raw))
    except Exception as e:  # noqa: BLE001
        return ("invalid", f"jsonschema {ref} compilation failed: {e}")
    return None


def _load_unit(case: str, tmp_path):
    with open(os.path.join(CORPUS, case + ".input"), encoding="utf-8") as f:
        expand_txtar(f.read(), str(tmp_path))
    policies = []
    for dirpath, _dirs, files in os.walk(tmp_path):
        for fn in sorted(files):
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, tmp_path)
            with open(path, encoding="utf-8") as f:
                for pol in parse_policies(f.read(), source=rel):
                    policies.append((rel, pol))
    return policies


def _norm_err(e: dict) -> dict:
    out = {
        "file": e.get("file", ""),
        "error": (e.get("error") or "").strip(),
        "description": (e.get("description") or "").strip(),
    }
    pos = e.get("position")
    if pos:
        out["position"] = {
            "line": pos.get("line", 0),
            "column": pos.get("column", 0),
            "path": pos.get("path", ""),
        }
    return out


def _key(e: dict):
    pos = e.get("position", {})
    return (e["file"], e["error"], pos.get("line", 0), pos.get("column", 0), pos.get("path", ""))


@pytest.mark.parametrize("case", CASES)
def test_compile_case(case, tmp_path):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = yaml.safe_load(f) or {}

    policies = _load_unit(case, tmp_path)
    repo = {p.fqn(): p for _rel, p in policies}
    main = next(p for rel, p in policies if rel == tc["mainDef"])

    want_errors = [_norm_err(w) for w in tc.get("wantErrors") or []]
    if want_errors:
        with pytest.raises(CompileError) as exc:
            compile_policy(main, repo, schema_check=_schema_check)
        have_errors = [_norm_err(d.to_dict()) for d in exc.value.details]

        def full_key(e):
            # descriptions embedding third-party diagnostics compare by prefix
            desc = e["description"]
            if e["error"] in _PREFIX_KINDS:
                desc = desc.split("`: ", 1)[0]
            elif desc.startswith("Failed to load") and (": jsonschema" in desc):
                desc = desc.split(": jsonschema", 1)[0]
            return _key(e) + (desc,)

        assert sorted(map(full_key, want_errors)) == sorted(map(full_key, have_errors)), (
            f"{case}:\nwant={json.dumps(want_errors, indent=1)}\n"
            f"have={json.dumps(have_errors, indent=1)}"
        )
        return

    compiled = compile_policy(main, repo, schema_check=_schema_check)

    want_vars = tc.get("wantVariables") or []
    if want_vars:
        # the reference records per-scope USED sets; compile each scope's
        # policy from the same unit and derive its used sets
        by_scope = {}
        for rel, p in policies:
            if p.kind == main.kind:
                c = compile_policy(p, repo, schema_check=_schema_check)
                by_scope[c.scope] = c
        for want in want_vars:
            c = by_scope[want.get("scope", "")]
            used_c, used_v = _used_sets(c)
            assert sorted(want.get("constants", [])) == sorted(used_c), (case, want.get("scope"))
            assert sorted(want.get("variables", [])) == sorted(used_v), (case, want.get("scope"))
            for dr_want in want.get("derivedRoles", []) or []:
                dr = c.derived_roles[dr_want["name"]]
                dr_c, dr_v = _used_sets_exprs([dr.condition], dr.params)
                assert sorted(dr_want.get("constants", [])) == sorted(dr_c), (case, dr_want["name"])
                assert sorted(dr_want.get("variables", [])) == sorted(dr_v), (case, dr_want["name"])


def _exprs_of(cond):
    if cond is None:
        return
    if cond.kind == "expr":
        if cond.expr is not None:
            yield cond.expr.node
        return
    for c in cond.children:
        yield from _exprs_of(c)


def _used_sets(compiled):
    nodes = []
    for r in compiled.rules:
        nodes.extend(_exprs_of(getattr(r, "condition", None)))
        out = getattr(r, "output", None)
        if out is not None:
            for e in (out.rule_activated, out.condition_not_met):
                if e is not None:
                    nodes.append(e.node)
    return _used_from_nodes(nodes, compiled.params)


def _used_sets_exprs(conds, params):
    nodes = []
    for c in conds:
        nodes.extend(_exprs_of(c))
    return _used_from_nodes(nodes, params)


def _used_from_nodes(nodes, params):
    var_defs = {v.name: v.expr.node for v in params.ordered_variables}
    used_vars = set()
    frontier = set()
    for n in nodes:
        frontier |= _variable_refs(n) & set(var_defs)
    while frontier:
        name = frontier.pop()
        if name in used_vars:
            continue
        used_vars.add(name)
        frontier |= _variable_refs(var_defs[name]) & set(var_defs)
    used_consts = set()
    for n in nodes:
        used_consts |= _constant_refs(n) & set(params.constants)
    for name in used_vars:
        used_consts |= _constant_refs(var_defs[name]) & set(params.constants)
    return sorted(used_consts), sorted(used_vars)
