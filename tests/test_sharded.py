"""Sharded serving pool: routing, per-shard fault domains, recovery.

The tentpole acceptance drill lives here: a shard-scoped fault
(``submit_raise:1.0,shard:0`` through the engine/faults.py grammar) trips
ONLY that shard's breaker; the router keeps traffic on the remaining lanes
with zero lost requests, and recovery half-opens only the sick shard.
"""

import concurrent.futures
import time

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine.faults import FaultInjector
from cerbos_tpu.engine.shards import build_shard_pool
from cerbos_tpu.observability import metrics
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu.evaluator import TpuEvaluator

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
    )


def effects(outs):
    return [{a: (e.effect, e.policy) for a, e in o.actions.items()} for o in outs]


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


def numpy_pool(rt, n_shards=4, fault_spec="", breaker_conf=None, **kw):
    """A pool over the numpy backend — fast, no device needed, but the full
    shard topology (clones, per-lane breakers, router) is real."""
    base = TpuEvaluator(rt, use_jax=False, min_device_batch=1)
    return build_shard_pool(
        base,
        n_shards=n_shards,
        max_wait_ms=kw.pop("max_wait_ms", 0.0),
        request_timeout_s=kw.pop("request_timeout_s", 10.0),
        fault_spec=fault_spec,
        breaker_conf=breaker_conf or {},
        **kw,
    )


class TestPoolTopology:
    def test_clone_per_shard_shares_lowered_table(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=4)
        try:
            assert len(pool.shards) == 4
            evs = [lane.evaluator for lane in pool.shards]
            assert len({id(e) for e in evs}) == 4  # distinct clones
            base_lowered = evs[0].lowered
            assert all(e.lowered is base_lowered for e in evs)  # shared lowering
            assert all(e.rule_table is rt for e in evs)
            # per-shard mutable state is NOT shared
            assert len({id(e.packer) for e in evs}) == 4
            assert [lane.shard_id for lane in pool.shards] == [0, 1, 2, 3]
        finally:
            pool.close()

    def test_parity_and_balanced_routing(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=4)
        reqs = [[inp(i)] for i in range(32)]
        try:
            with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
                futs = [ex.submit(pool.check, r) for r in reqs]
                outs = [f.result(timeout=15)[0] for f in futs]
            assert effects(outs) == effects(oracle(rt, [r[0] for r in reqs]))
            assert sum(pool.routed) == 32
            assert all(c > 0 for c in pool.routed)  # every lane took traffic
            assert pool.routing_imbalance() < 4.0
        finally:
            pool.close()

    def test_round_robin_routing_is_even(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=4, routing="round_robin")
        try:
            for i in range(16):
                pool.check([inp(i)])
            assert pool.routed == [4, 4, 4, 4]
            assert pool.routing_imbalance() == 1.0
        finally:
            pool.close()

    def test_pool_stats_aggregate_lane_stats(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=2)
        try:
            for i in range(8):
                pool.check([inp(i)])
            stats = pool.stats
            assert stats["batched_requests"] == sum(
                lane.stats["batched_requests"] for lane in pool.shards
            )
            assert stats["routed"] == pool.routed
            per_shard = pool.shard_stats()
            assert [s["shard"] for s in per_shard] == [0, 1]
            assert all(s["breaker_state"] == "closed" for s in per_shard)
        finally:
            pool.close()

    def test_refresh_shards_points_every_clone_at_new_table(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=3, fault_spec="seed:1")  # injector-wrapped lanes
        rt2 = table()
        try:
            pool.refresh_shards(rt2)
            for lane in pool.shards:
                ev = getattr(lane.evaluator, "_ev", lane.evaluator)
                assert ev.rule_table is rt2  # the REAL evaluator, not the wrapper
        finally:
            pool.close()

    def test_health_state_aggregates_for_readiness(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=3, breaker_conf={"failureThreshold": 1, "probeBackoffBaseMs": 600000})
        try:
            assert pool.health_state() == "closed"
            # one sick lane is a capacity event, not an availability event
            pool.shards[0].health.record_failure()
            assert pool.shards[0].health.state == "open"
            assert pool.health_state() == "closed"
            # every lane open -> the pool reports open
            for lane in pool.shards[1:]:
                lane.health.record_failure()
            assert pool.health_state() == "open"
        finally:
            pool.close()

    def test_shard_labeled_metric_families_render(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=2)
        try:
            for i in range(6):
                pool.check([inp(i)])
            text = metrics().render()
            for fam in ("cerbos_tpu_batcher_inflight", "cerbos_tpu_batch_occupancy", "cerbos_tpu_breaker_state"):
                assert f'{fam}{{shard="0"}}' in text, fam
                assert f'{fam}{{shard="1"}}' in text, fam
            assert 'cerbos_tpu_batch_stage_seconds_bucket{stage="pack",shard=' in text
        finally:
            pool.close()


@pytest.mark.chaos
class TestShardFaultDomain:
    def test_shard_scoped_fault_trips_only_that_lane(self):
        """Acceptance drill: shard 0 faults at 100%; ONLY its breaker trips,
        the router keeps serving on the other lanes, and every request gets
        a correct answer — zero lost requests."""
        rt = table()
        pool = numpy_pool(
            rt,
            n_shards=4,
            fault_spec="submit_raise:1.0,shard:0",
            breaker_conf={"failureThreshold": 2, "probeBackoffBaseMs": 600000},
        )
        reqs = [[inp(i)] for i in range(60)]
        try:
            # only lane 0 carries the injector
            assert isinstance(pool.shards[0].evaluator, FaultInjector)
            assert not any(isinstance(l.evaluator, FaultInjector) for l in pool.shards[1:])
            with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
                futs = [ex.submit(pool.check, r) for r in reqs]
                outs = [f.result(timeout=20)[0] for f in futs]  # nothing raises, nothing hangs
            # zero lost requests, all bit-exact vs the oracle
            assert effects(outs) == effects(oracle(rt, [r[0] for r in reqs]))
            # fault domain: exactly the sick shard's breaker tripped
            assert pool.shards[0].health.state == "open"
            assert pool.shards[0].health.stats["trips"] == 1
            for lane in pool.shards[1:]:
                assert lane.health.state == "closed"
                assert lane.health.stats["trips"] == 0
            # service continued at (N-1)/N: healthy lanes did real device batches
            healthy_batches = sum(l.stats["batches"] for l in pool.shards[1:])
            assert healthy_batches > 0
            # the pool is still "available" for readiness purposes
            assert pool.health_state() == "closed"
            # once open, the router steers admission off the sick lane
            routed_before = pool.routed[0]
            for i in range(12):
                pool.check([inp(100 + i)])
            assert pool.routed[0] == routed_before
        finally:
            pool.close()

    def test_recovery_half_opens_only_the_sick_shard(self):
        rt = table()
        pool = numpy_pool(
            rt,
            n_shards=3,
            fault_spec="submit_raise:1.0,shard:0",
            breaker_conf={
                "failureThreshold": 1,
                "probeBackoffBaseMs": 20,
                "probeBackoffCapMs": 100,
            },
        )
        try:
            # trip lane 0: route to it directly so the injector fires
            sick = pool.shards[0]
            for i in range(3):
                sick.check([inp(i)])
            assert sick.health.state == "open"
            # the device heals (chaos drill flips the fault off at runtime)
            sick.evaluator.spec.pop("submit_raise")
            deadline = time.monotonic() + 10.0
            while sick.health.state != "closed" and time.monotonic() < deadline:
                # pool traffic: the router's probe trickle donates inputs
                pool.check([inp(1)])
                time.sleep(0.01)
            assert sick.health.state == "closed"
            assert sick.health.stats["probes"] >= 1
            # the healthy lanes never probed or tripped — recovery was scoped
            for lane in pool.shards[1:]:
                assert lane.health.stats["trips"] == 0
                assert lane.health.stats["probes"] == 0
            # live traffic is back on the recovered lane's device path
            before = sick.stats["batches"]
            sick.check([inp(5)])
            assert sick.stats["batches"] == before + 1
        finally:
            pool.close()

    def test_unscoped_fault_spec_wraps_every_lane(self):
        rt = table()
        pool = numpy_pool(rt, n_shards=3, fault_spec="seed:9")
        try:
            assert all(isinstance(l.evaluator, FaultInjector) for l in pool.shards)
        finally:
            pool.close()


@pytest.mark.multichip
class TestDeviceMeshPool:
    """The jax path over the virtual 8-device mesh (conftest forces
    --xla_force_host_platform_device_count=8 in-process)."""

    def _jax_pool(self, rt, **kw):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("virtual multi-device mesh unavailable")
        base = TpuEvaluator(rt, use_jax=True, min_device_batch=2)
        return build_shard_pool(base, max_wait_ms=1.0, **kw), base

    def test_one_lane_per_device_with_pinning(self):
        import jax

        rt = table()
        pool, base = self._jax_pool(rt)
        try:
            devices = jax.devices()
            assert len(pool.shards) == len(devices)
            pinned = [lane.evaluator.device for lane in pool.shards]
            assert pinned == devices  # one lane per device, in order
        finally:
            pool.close()

    def test_mesh_parity_and_per_lane_flight_records(self):
        from cerbos_tpu.engine.flight import recorder

        rt = table()
        pool, base = self._jax_pool(rt)
        reqs = [[inp(i), inp(i + 100)] for i in range(24)]
        try:
            with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
                futs = [ex.submit(pool.check, r) for r in reqs]
                outs = [f.result(timeout=60) for f in futs]
            flat_in = [i for r in reqs for i in r]
            flat_out = [o for ro in outs for o in ro]
            assert effects(flat_out) == effects(oracle(rt, flat_in))
            # the flight recorder can replay a single lane's history
            busy = [i for i, c in enumerate(pool.routed) if c > 0]
            assert busy, pool.routed
            lane_records = recorder().lane(busy[0])
            assert lane_records and all(r.get("shard") == busy[0] for r in lane_records)
        finally:
            pool.close()
