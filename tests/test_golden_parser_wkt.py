"""Reference parser_wkt corpus: well-known-type unmarshalling.

Mirrors internal/parser/wkt_test.go TestUnmarshalWKT: ListValue, NullValue,
Struct, Value, UInt64Value, Empty and Timestamp fields in plain, repeated
and map positions, plus a nested message, parsed identically from YAML and
JSON; type mismatches report goccy-style errors with positions.

Representation notes vs the Go test (which compares proto objects):
  - singular NullValue / null-valued Value fields are unset in our dict form
    (protojson also omits them), so they are absent from WANT;
  - UInt64Value renders as a decimal string (protojson convention);
  - Timestamps normalize to canonical protojson form (UTC, Z suffix,
    0/3/6/9 fractional digits).
"""

import os

import pytest

from cerbos_tpu.policy import protoschema as S
from cerbos_tpu.policy.protoyaml import unmarshal

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "parser_wkt")

_LIST = [None, None, None, 1, "two", True, False,
         {"three": "four", "five": 6},
         ["seven", 8, {"nine": 10}]]
_STRUCT = {
    "one": None, "two": 3, "four": "five", "six": True, "seven": False,
    "eight": {"nine": 10, "eleven": "twelve"},
    "thirteen": [14, "fifteen"],
}

WANT = {
    "listValue": _LIST,
    "repeatedListValue": [[None, 1, "two"], [True, False],
                          [{"three": "four", "five": 6}, ["seven", 8, {"nine": 10}]]],
    "listValueMap": {"foo": [None, 1, "two"], "bar": [True, False],
                     "baz": [{"three": "four", "five": 6}, ["seven", 8, {"nine": 10}]]},
    "repeatedNullValue": [None, None, None],
    "nullValueMap": {"foo": None, "bar": None, "baz": None},
    "struct": _STRUCT,
    "repeatedStruct": [
        {"one": None, "two": 3, "four": "five"},
        {"six": True, "seven": False},
        {"eight": {"nine": 10, "eleven": "twelve"}},
        {"thirteen": [14, "fifteen"]},
    ],
    "structMap": {
        "foo": {"one": None, "two": 3, "four": "five"},
        "bar": {"six": True, "seven": False},
        "baz": {"eight": {"nine": 10, "eleven": "twelve"}},
        "qux": {"thirteen": [14, "fifteen"]},
    },
    "valueNumber": 1,
    "valueString": "two",
    "valueBool": True,
    "valueStruct": {"three": 4, "five": "six"},
    "valueList": [7, "eight"],
    "repeatedValue": [None, 1, "two", True, False,
                      {"three": "four", "five": 6},
                      ["seven", 8, {"nine": 10}]],
    "valueMap": {"foo": None, "bar": 1, "baz": "two", "qux": True, "quux": False,
                 "quuux": {"three": "four", "five": 6},
                 "quuuux": ["seven", 8, {"nine": 10}]},
    "uint64WrapperNumber": "1",
    "uint64WrapperString": "2",
    "repeatedUint64Wrapper": ["1", "2"],
    "uint64WrapperMap": {"foo": "1", "bar": "2"},
    "empty": {},
    "repeatedEmpty": [{}, {}],
    "emptyMap": {"foo": {}, "bar": {}},
    "timestamp": "2026-06-15T10:31:01.121Z",
    "repeatedTimestamp": ["2026-06-15T10:31:01Z", "2026-06-15T10:31:01.121161Z"],
    "timestampMap": {"foo": "2026-06-15T10:31:01Z", "bar": "2026-06-15T10:31:01.121161239Z"},
}
WANT["nested"] = {k: v for k, v in WANT.items()}


def _norm(v):
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


@pytest.mark.parametrize("name", ["valid.yaml", "valid.json"])
def test_wkt_valid(name):
    with open(os.path.join(CORPUS, name), "rb") as f:
        res = unmarshal(f.read(), S.WELL_KNOWN_TYPES)
    assert not res.errors, [e.render() for e in res.errors]
    assert len(res.docs) == 1
    assert _norm(res.docs[0].message) == _norm(WANT)


@pytest.mark.parametrize(
    "name,line,column",
    [("invalid.yaml", 2, 9), ("invalid.json", 2, 13)],
)
def test_wkt_invalid(name, line, column):
    with open(os.path.join(CORPUS, name), "rb") as f:
        res = unmarshal(f.read(), S.WELL_KNOWN_TYPES)
    assert len(res.errors) == 1
    e = res.errors[0]
    assert e.kind == "KIND_PARSE_ERROR"
    assert e.message == "expected map got String"
    assert (e.line, e.column, e.path) == (line, column, "$.struct")
