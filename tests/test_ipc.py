"""The multi-process front door's seam: the ticket queue (engine/ipc.py).

In-process pairs of ``BatcherIpcServer`` (over a ``BatchingEvaluator`` backed
by the CPU oracle) and ``RemoteBatcherClient`` on a temp unix socket prove the
PR's acceptance criteria at the unit level: decision parity with the
single-process path, deadline propagation across the process boundary,
zero-loss settling when the batcher side dies mid-flight, backpressure and
wedged-ring fallbacks, and the pool readiness ladder (warming until the shared
batcher's first SERVING report, degraded-but-live after a disconnect).
"""

import asyncio
import re
import threading
import time

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine.batcher import BatchingEvaluator, DeadlineExceeded, _BatchFailed
from cerbos_tpu.engine.health import DeviceHealth
from cerbos_tpu.engine.ipc import (
    BatcherIpcServer,
    RemoteBatcherClient,
    decode_inputs,
    decode_outputs,
    encode_inputs,
    encode_outputs,
)
from cerbos_tpu import observability as obs
from cerbos_tpu.observability import merge_metrics_texts, relabel_metrics_text
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
        request_id=f"rq{i}",
    )


def effects(outs):
    return [{a: (e.effect, e.policy) for a, e in o.actions.items()} for o in outs]


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


class OracleEvaluator:
    """CPU-oracle-backed streaming evaluator (the test_chaos harness): the
    ticket queue's behavior must not depend on jax being importable."""

    def __init__(self, rt, submit_delay_s: float = 0.0):
        self.rule_table = rt
        self.schema_mgr = None
        self.submit_delay_s = submit_delay_s
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return oracle(self.rule_table, inputs, params)

    def submit(self, inputs, params=None):
        if self.submit_delay_s:
            time.sleep(self.submit_delay_s)
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


def wait_for(cond, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


@pytest.fixture()
def rt():
    return table()


def make_pair(
    tmp_path,
    rt,
    submit_delay_s=0.0,
    readiness=None,
    max_outstanding=4096,
    faults=None,
    health=None,
    request_timeout_s=30.0,
):
    batcher = BatchingEvaluator(
        OracleEvaluator(rt, submit_delay_s=submit_delay_s), max_wait_ms=1.0, health=health
    )
    server = BatcherIpcServer(
        str(tmp_path / "batcher.sock"),
        batcher,
        readiness=readiness,
        max_outstanding=max_outstanding,
        faults=faults,
    )
    server.start()
    client = RemoteBatcherClient(
        server.socket_path,
        rt,
        request_timeout_s=request_timeout_s,
        worker_label="fe-test",
        status_poll_s=0.05,
        connect_retry_s=0.05,
    )
    assert wait_for(client._connected.is_set)
    return batcher, server, client


class TestCodec:
    def test_inputs_roundtrip(self, rt):
        inputs = [inp(i) for i in range(7)]
        decoded = decode_inputs(encode_inputs(inputs))
        assert effects(oracle(rt, decoded)) == effects(oracle(rt, inputs))
        assert [d.request_id for d in decoded] == [i.request_id for i in inputs]
        # attrs arrive pre-normalized: no __post_init__ re-run on decode
        assert decoded[0].principal.id == "u0"
        assert decoded[3].resource.attr["public"] is True

    def test_outputs_roundtrip(self, rt):
        outs = oracle(rt, [inp(i) for i in range(7)])
        decoded = decode_outputs(encode_outputs(outs))
        assert effects(decoded) == effects(outs)
        assert [d.resource_id for d in decoded] == [o.resource_id for o in outs]


class TestTicketQueue:
    def test_decision_parity_with_single_process_path(self, tmp_path, rt):
        """Acceptance pin: the multi-process path must produce bit-identical
        decisions to the single-process batcher/oracle path."""
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            inputs = [inp(i) for i in range(64)]
            remote = client.check(inputs)
            assert effects(remote) == effects(batcher.check(inputs))
            assert effects(remote) == effects(oracle(rt, inputs))
            assert client.stats["oracle_fallbacks"] == 0
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_check_await_parity(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            inputs = [inp(i) for i in range(16)]

            async def go():
                return await client.check_await(inputs)

            remote = asyncio.run(go())
            assert effects(remote) == effects(oracle(rt, inputs))
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_expired_deadline_raises(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            with pytest.raises(DeadlineExceeded):
                client.check([inp(1)], deadline=time.monotonic() - 0.01)
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_deadline_crosses_process_boundary(self, tmp_path, rt):
        """The deadline rides the ticket as relative remaining time and the
        batcher drops expired work at drain time."""
        batcher, server, client = make_pair(tmp_path, rt, submit_delay_s=0.3)
        try:
            with pytest.raises(DeadlineExceeded):
                # saturate the drain loop so the second ticket expires queued
                t = threading.Thread(target=lambda: client.check([inp(0)]))
                t.start()
                try:
                    client.check([inp(1)], deadline=time.monotonic() + 0.05)
                finally:
                    t.join()
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_batcher_down_serves_oracle_fast(self, tmp_path, rt):
        client = RemoteBatcherClient(
            str(tmp_path / "nobody-home.sock"),
            rt,
            status_poll_s=0.05,
            connect_retry_s=0.05,
        )
        try:
            t0 = time.perf_counter()
            outs = client.check([inp(i) for i in range(8)])
            # no connection: the fallback must not wait out any timeout
            assert time.perf_counter() - t0 < 1.0
            assert effects(outs) == effects(oracle(rt, [inp(i) for i in range(8)]))
            assert client.stats["oracle_fallbacks"] == 1
        finally:
            client.close()

    def test_midflight_death_loses_zero_requests(self, tmp_path, rt):
        """Kill the batcher side with tickets in flight: every waiter must
        settle promptly via the local oracle with correct decisions."""
        batcher, server, client = make_pair(tmp_path, rt, submit_delay_s=0.5)
        results = {}

        def one(i):
            results[i] = client.check([inp(i)])

        threads = [threading.Thread(target=one, args=(i,)) for i in range(12)]
        try:
            for t in threads:
                t.start()
            assert wait_for(lambda: len(client._pending) > 0)
            server.close()
            batcher.close()
            t0 = time.perf_counter()
            for t in threads:
                t.join(timeout=10.0)
            assert all(not t.is_alive() for t in threads)
            # settled by the disconnect, not by the 30s request timeout
            assert time.perf_counter() - t0 < 10.0
            assert len(results) == 12
            for i, outs in results.items():
                assert effects(outs) == effects(oracle(rt, [inp(i)]))
        finally:
            client.close()

    def test_breaker_open_refusal_serves_frontend_oracle(self, tmp_path, rt):
        health = DeviceHealth(failure_threshold=1)
        health.record_failure()
        assert health.state == "open"
        batcher, server, client = make_pair(tmp_path, rt, health=health)
        try:
            outs = client.check([inp(i) for i in range(4)])
            assert effects(outs) == effects(oracle(rt, [inp(i) for i in range(4)]))
            assert client.stats["oracle_fallbacks"] == 1
            # the refusal reason travels back over the queue
            assert client.m_fallbacks.get("breaker_open") >= 1
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_wedged_ring_falls_back_via_timeout(self, tmp_path, rt):
        batcher, server, client = make_pair(
            tmp_path, rt, faults={"ipc_wedge_after": 1}, request_timeout_s=0.3
        )
        try:
            assert effects(client.check([inp(0)])) == effects(oracle(rt, [inp(0)]))
            t0 = time.perf_counter()
            outs = client.check([inp(1)])
            assert 0.2 < time.perf_counter() - t0 < 5.0
            assert effects(outs) == effects(oracle(rt, [inp(1)]))
            assert server.stats["wedged_drops"] >= 1
            assert client.stats["oracle_fallbacks"] == 1
        finally:
            client.close()
            server.close()
            batcher.close()

    def test_full_queue_backpressure(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt, submit_delay_s=0.3, max_outstanding=1)
        try:
            full0 = client.m_full.value
            t = threading.Thread(target=lambda: client.check([inp(0)]))
            t.start()
            assert wait_for(lambda: server._outstanding >= 1)
            outs = client.check([inp(1)])
            t.join()
            assert effects(outs) == effects(oracle(rt, [inp(1)]))
            assert server.stats["rejected_full"] >= 1
            # full refusals are counted ONCE per pool, on the front end that
            # receives the ERR — the batcher keeps only the stats entry. In
            # this in-process harness both sides alias the same registry
            # instrument, so an exact +1 proves neither side double-counts.
            assert client.m_full.value == full0 + 1
        finally:
            client.close()
            server.close()
            batcher.close()


class TestPoolReadiness:
    def test_warming_until_first_ready_then_degraded_on_disconnect(self, tmp_path, rt):
        status = {"status": "warming"}
        batcher, server, client = make_pair(tmp_path, rt, readiness=lambda: dict(status))
        try:
            assert wait_for(lambda: client._last_status is not None)
            assert client.remote_status()["status"] == "warming"
            # batcher warmup completes → the pool opens
            status["status"] = "ready"
            assert wait_for(lambda: client.remote_status()["status"] == "ready")
            # batcher dies → degraded-but-live, never back to warming
            server.close()
            batcher.close()
            assert wait_for(lambda: client.remote_status()["status"] == "degraded")
            assert client.remote_status()["attached"] is False
        finally:
            client.close()

    def test_never_attached_reports_warming(self, tmp_path, rt):
        client = RemoteBatcherClient(
            str(tmp_path / "nobody-home.sock"), rt, status_poll_s=0.05, connect_retry_s=0.05
        )
        try:
            assert client.remote_status()["status"] == "warming"
        finally:
            client.close()


class TestControlFrames:
    def test_flight_and_metrics_frames(self, tmp_path, rt):
        batcher, server, client = make_pair(tmp_path, rt, readiness=lambda: {"status": "ready"})
        try:
            client.check([inp(i) for i in range(8)])
            dump = client.fetch_flight()
            assert "flight" in dump and "pid" in dump
            assert {"capacity", "batches", "events"} <= set(dump["flight"])
            text = client.fetch_metrics_text()
            assert "cerbos_tpu_ipc_ring_depth" in text
            assert "cerbos_tpu_batcher_batches_total" in text
        finally:
            client.close()
            server.close()
            batcher.close()


class TestCheckAsync:
    """BatchingEvaluator.check_async refuses via the settled future so the
    front-end process (not the batcher) serves the oracle."""

    def test_settles_with_result(self, rt):
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        try:
            fut = b.check_async([inp(i) for i in range(4)])
            outs = fut.result(timeout=5.0)
            assert effects(outs) == effects(oracle(rt, [inp(i) for i in range(4)]))
        finally:
            b.close()

    def test_expired_deadline_settles_exception(self, rt):
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        try:
            fut = b.check_async([inp(0)], deadline=time.monotonic() - 1.0)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=1.0)
        finally:
            b.close()

    def test_breaker_open_settles_batch_failed(self, rt):
        health = DeviceHealth(failure_threshold=1)
        health.record_failure()
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0, health=health)
        try:
            fut = b.check_async([inp(0)])
            with pytest.raises(_BatchFailed) as ei:
                fut.result(timeout=1.0)
            assert ei.value.reason == "breaker_open"
        finally:
            b.close()

    def test_closed_batcher_settles_dead(self, rt):
        b = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=1.0)
        b.close()
        fut = b.check_async([inp(0)])
        with pytest.raises(_BatchFailed) as ei:
            fut.result(timeout=1.0)
        assert ei.value.reason == "batcher_dead"


class TestMetricsRelabel:
    def test_relabel_injects_worker_label(self):
        text = '# TYPE a counter\na 1\nb{x="1"} 2\n'
        out = relabel_metrics_text(text, "worker", "fe1")
        assert 'a{worker="fe1"} 1' in out
        assert 'b{worker="fe1",x="1"} 2' in out
        assert "# TYPE a counter" in out

    def test_merge_dedupes_family_comments(self):
        a = "# TYPE m counter\n# HELP m help\nm{worker=\"fe1\"} 1\n"
        b = "# TYPE m counter\n# HELP m help\nm{worker=\"batcher\"} 2\n"
        merged = merge_metrics_texts(a, b)
        assert merged.count("# TYPE m counter") == 1
        assert merged.count("# HELP m help") == 1
        assert 'm{worker="fe1"} 1' in merged
        assert 'm{worker="batcher"} 2' in merged

    def test_relabel_and_merge_cover_budget_and_pressure_families(self):
        """The PR 9 families flow through the purely textual relabel/merge
        machinery like any other series: labeled histograms keep their
        stage/shard labels, gauges pick up the worker label, and merging a
        front end's text with the batcher's keeps both processes' series."""
        fe = (
            "# TYPE cerbos_tpu_request_stage_seconds histogram\n"
            'cerbos_tpu_request_stage_seconds_bucket{stage="ipc_encode",shard="0",le="0.001"} 3\n'
            'cerbos_tpu_request_stage_seconds_sum{stage="ipc_encode",shard="0"} 0.002\n'
            "# TYPE cerbos_tpu_decisions_total counter\n"
            'cerbos_tpu_decisions_total{api="check",outcome="deadline_met"} 7\n'
            "# TYPE cerbos_tpu_pressure_score gauge\n"
            "cerbos_tpu_pressure_score 0.25\n"
        )
        batcher = (
            "# TYPE cerbos_tpu_request_stage_seconds histogram\n"
            'cerbos_tpu_request_stage_seconds_bucket{stage="queue_wait",shard="1",le="0.001"} 5\n'
            "# TYPE cerbos_tpu_pressure_score gauge\n"
            "cerbos_tpu_pressure_score 0.75\n"
        )
        fe_rel = relabel_metrics_text(fe, "worker", "fe0")
        b_rel = relabel_metrics_text(batcher, "worker", "batcher")
        assert (
            'cerbos_tpu_request_stage_seconds_bucket{worker="fe0",stage="ipc_encode",shard="0",le="0.001"} 3'
            in fe_rel
        )
        assert 'cerbos_tpu_decisions_total{worker="fe0",api="check",outcome="deadline_met"} 7' in fe_rel
        assert 'cerbos_tpu_pressure_score{worker="batcher"} 0.75' in b_rel
        merged = merge_metrics_texts(fe_rel, b_rel)
        assert merged.count("# TYPE cerbos_tpu_request_stage_seconds histogram") == 1
        assert merged.count("# TYPE cerbos_tpu_pressure_score gauge") == 1
        assert 'cerbos_tpu_pressure_score{worker="fe0"} 0.25' in merged
        assert 'cerbos_tpu_pressure_score{worker="batcher"} 0.75' in merged
        assert (
            'cerbos_tpu_request_stage_seconds_bucket{worker="batcher",stage="queue_wait",shard="1",le="0.001"} 5'
            in merged
        )

    def test_relabel_and_merge_cover_transport_families(self):
        """The PR 10 transport families flow through the textual machinery
        like any other series: transport/dir labels survive relabeling, and
        cerbos_tpu_ipc_full_total — registered by BOTH sides of the queue —
        dedupes its family comment when the two processes' texts merge."""
        fe = (
            "# TYPE cerbos_tpu_ipc_frame_bytes histogram\n"
            'cerbos_tpu_ipc_frame_bytes_bucket{transport="shm",dir="out",le="1024"} 9\n'
            'cerbos_tpu_ipc_frame_bytes_sum{transport="shm",dir="out"} 4096\n'
            "# TYPE cerbos_tpu_ipc_full_total counter\n"
            'cerbos_tpu_ipc_full_total{transport="shm"} 2\n'
            "# TYPE cerbos_tpu_ipc_client_rtt_seconds histogram\n"
            'cerbos_tpu_ipc_client_rtt_seconds_bucket{transport="shm",le="0.005"} 11\n'
        )
        batcher = (
            "# TYPE cerbos_tpu_ipc_ring_depth gauge\n"
            'cerbos_tpu_ipc_ring_depth{transport="shm"} 3\n'
            "# TYPE cerbos_tpu_ipc_full_total counter\n"
            'cerbos_tpu_ipc_full_total{transport="uds"} 1\n'
        )
        fe_rel = relabel_metrics_text(fe, "worker", "fe0")
        b_rel = relabel_metrics_text(batcher, "worker", "batcher")
        assert (
            'cerbos_tpu_ipc_frame_bytes_bucket{worker="fe0",transport="shm",dir="out",le="1024"} 9'
            in fe_rel
        )
        assert 'cerbos_tpu_ipc_client_rtt_seconds_bucket{worker="fe0",transport="shm",le="0.005"} 11' in fe_rel
        merged = merge_metrics_texts(fe_rel, b_rel)
        assert merged.count("# TYPE cerbos_tpu_ipc_full_total counter") == 1
        assert 'cerbos_tpu_ipc_full_total{worker="fe0",transport="shm"} 2' in merged
        assert 'cerbos_tpu_ipc_full_total{worker="batcher",transport="uds"} 1' in merged
        assert 'cerbos_tpu_ipc_ring_depth{worker="batcher",transport="shm"} 3' in merged

    def test_relabel_and_merge_cover_policy_analysis_families(self):
        """The PR 14 static-analysis families are multi-label gauges and
        reason-coded counters; both processes publish them (the batcher
        owns the live table, a front end may analyze a candidate bundle),
        so the merged scrape must keep each worker's verdicts distinct."""
        batcher = (
            "# TYPE cerbos_tpu_policy_analysis_total gauge\n"
            'cerbos_tpu_policy_analysis_total{class="device",reason="ok"} 75\n'
            'cerbos_tpu_policy_analysis_total{class="oracle-only",reason="operand_unsupported"} 3\n'
            "# TYPE cerbos_tpu_cond_compile_unsupported_total counter\n"
            'cerbos_tpu_cond_compile_unsupported_total{reason="unsupported_membership"} 3\n'
        )
        fe = (
            "# TYPE cerbos_tpu_policy_analysis_total gauge\n"
            'cerbos_tpu_policy_analysis_total{class="tagged-fallback",reason="eq_collection_operand"} 49\n'
            "# TYPE cerbos_tpu_cond_compile_unsupported_total counter\n"
            'cerbos_tpu_cond_compile_unsupported_total{reason="undefined_global"} 1\n'
        )
        b_rel = relabel_metrics_text(batcher, "worker", "batcher")
        fe_rel = relabel_metrics_text(fe, "worker", "fe0")
        assert (
            'cerbos_tpu_policy_analysis_total{worker="batcher",class="oracle-only",reason="operand_unsupported"} 3'
            in b_rel
        )
        merged = merge_metrics_texts(b_rel, fe_rel)
        assert merged.count("# TYPE cerbos_tpu_policy_analysis_total gauge") == 1
        assert merged.count("# TYPE cerbos_tpu_cond_compile_unsupported_total counter") == 1
        assert 'cerbos_tpu_policy_analysis_total{worker="batcher",class="device",reason="ok"} 75' in merged
        assert (
            'cerbos_tpu_policy_analysis_total{worker="fe0",class="tagged-fallback",reason="eq_collection_operand"} 49'
            in merged
        )
        assert 'cerbos_tpu_cond_compile_unsupported_total{worker="batcher",reason="unsupported_membership"} 3' in merged
        assert 'cerbos_tpu_cond_compile_unsupported_total{worker="fe0",reason="undefined_global"} 1' in merged

    def test_relabel_and_merge_cover_rollout_families(self):
        """The rollout families span both processes: the batcher owns the
        rollout machinery (stage counters, epoch gauge), while each front
        end exports its own policy_epoch plus the skew gauge measuring lag
        behind the batcher's STATUS frames. A merged scrape must keep the
        per-worker epochs distinct — epoch disagreement across workers IS
        the mixed-epoch alert signal."""
        batcher = (
            "# TYPE cerbos_tpu_rollout_total counter\n"
            'cerbos_tpu_rollout_total{stage="gate",outcome="ok"} 4\n'
            'cerbos_tpu_rollout_total{stage="canary",outcome="rolled_back"} 1\n'
            "# TYPE cerbos_tpu_rollout_duration_seconds histogram\n"
            'cerbos_tpu_rollout_duration_seconds_bucket{stage="cutover",le="0.1"} 4\n'
            'cerbos_tpu_rollout_duration_seconds_sum{stage="cutover"} 0.12\n'
            "# TYPE cerbos_tpu_policy_epoch gauge\n"
            "cerbos_tpu_policy_epoch 7\n"
        )
        fe = (
            "# TYPE cerbos_tpu_policy_epoch gauge\n"
            "cerbos_tpu_policy_epoch 7\n"
            "# TYPE cerbos_tpu_policy_epoch_skew_seconds gauge\n"
            "cerbos_tpu_policy_epoch_skew_seconds 0.31\n"
        )
        b_rel = relabel_metrics_text(batcher, "worker", "batcher")
        fe_rel = relabel_metrics_text(fe, "worker", "fe0")
        assert 'cerbos_tpu_rollout_total{worker="batcher",stage="canary",outcome="rolled_back"} 1' in b_rel
        assert (
            'cerbos_tpu_rollout_duration_seconds_bucket{worker="batcher",stage="cutover",le="0.1"} 4'
            in b_rel
        )
        merged = merge_metrics_texts(b_rel, fe_rel)
        # policy_epoch is registered by BOTH sides: family comment dedupes,
        # both workers' series survive so skew is observable per process
        assert merged.count("# TYPE cerbos_tpu_policy_epoch gauge") == 1
        assert 'cerbos_tpu_policy_epoch{worker="batcher"} 7' in merged
        assert 'cerbos_tpu_policy_epoch{worker="fe0"} 7' in merged
        assert 'cerbos_tpu_policy_epoch_skew_seconds{worker="fe0"} 0.31' in merged
        assert 'cerbos_tpu_rollout_total{worker="batcher",stage="gate",outcome="ok"} 4' in merged

    def test_relabel_and_merge_cover_plan_families(self):
        """The batched-planner families ride the same textual machinery:
        mode/path labels survive relabeling, plan traffic booked under
        decisions_total{api="plan"} keeps its api dimension, and the
        plan-parity counters merge alongside the check-parity ones."""
        batcher = (
            "# TYPE cerbos_tpu_plan_batch_seconds histogram\n"
            'cerbos_tpu_plan_batch_seconds_bucket{mode="numpy",le="0.01"} 12\n'
            'cerbos_tpu_plan_batch_seconds_sum{mode="numpy"} 0.05\n'
            "# TYPE cerbos_tpu_plan_queries_total counter\n"
            'cerbos_tpu_plan_queries_total{path="device"} 900\n'
            'cerbos_tpu_plan_queries_total{path="symbolic"} 100\n'
            "# TYPE cerbos_tpu_plan_parity_checks_total counter\n"
            "cerbos_tpu_plan_parity_checks_total 40\n"
            "# TYPE cerbos_tpu_plan_parity_divergence_total counter\n"
            "cerbos_tpu_plan_parity_divergence_total 0\n"
        )
        fe = (
            "# TYPE cerbos_tpu_decisions_total counter\n"
            'cerbos_tpu_decisions_total{api="plan",outcome="deadline_met"} 31\n'
            'cerbos_tpu_decisions_total{api="plan",outcome="refused"} 4\n'
        )
        b_rel = relabel_metrics_text(batcher, "worker", "batcher")
        fe_rel = relabel_metrics_text(fe, "worker", "fe0")
        assert 'cerbos_tpu_plan_batch_seconds_bucket{worker="batcher",mode="numpy",le="0.01"} 12' in b_rel
        assert 'cerbos_tpu_plan_queries_total{worker="batcher",path="device"} 900' in b_rel
        assert 'cerbos_tpu_plan_parity_divergence_total{worker="batcher"} 0' in b_rel
        merged = merge_metrics_texts(b_rel, fe_rel)
        assert merged.count("# TYPE cerbos_tpu_plan_queries_total counter") == 1
        assert 'cerbos_tpu_decisions_total{worker="fe0",api="plan",outcome="refused"} 4' in merged
        assert 'cerbos_tpu_plan_parity_checks_total{worker="batcher"} 40' in merged


class TestTransportMetricsLint:
    def test_ipc_families_register_with_transport_labels(self, tmp_path, rt):
        """Extends the registry lint (test_tracing.TestMetricsLint) to the
        transport families, which only register once an ipc pair exists:
        conformant names, help text, and the transport label dimension in
        the documented position."""
        batcher, server, client = make_pair(tmp_path, rt)
        try:
            client.check([inp(0)])
            inst = obs.metrics().instruments()
            want = {
                "cerbos_tpu_ipc_ring_depth": (obs.GaugeVec, "transport"),
                "cerbos_tpu_ipc_full_total": (obs.CounterVec, "transport"),
                "cerbos_tpu_ipc_frame_bytes": (obs.HistogramVec, ("transport", "dir")),
                "cerbos_tpu_ipc_client_rtt_seconds": (obs.HistogramVec, "transport"),
                "cerbos_tpu_ipc_client_reconnects_total": (obs.CounterVec, "transport"),
            }
            for name, (typ, label) in want.items():
                m = inst.get(name)
                assert isinstance(m, typ), (name, type(m))
                assert m.label == label, (name, m.label)
                assert re.fullmatch(r"cerbos_tpu_[a-z0-9_]+", name), name
                assert m.help, f"metric {name!r} has no help text"
            # rendered exposition carries the label on every child series
            text = obs.metrics().render()
            for line in text.splitlines():
                if line.startswith("cerbos_tpu_ipc_client_rtt_seconds_bucket{"):
                    assert 'transport="' in line, line
        finally:
            client.close()
            server.close()
            batcher.close()
