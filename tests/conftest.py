import os
import sys

# Tests run on a virtual 8-device CPU mesh; the real TPU is exercised by
# bench.py and the driver's dryrun_multichip.
#
# The axon TPU plugin (PYTHONPATH=/root/.axon_site, hooked via a .pth at
# interpreter startup) initializes its backend inside every jax.backends()
# call even under JAX_PLATFORMS=cpu, and hangs indefinitely when the TPU
# tunnel is unreachable. Tests never need the real chip, so when the plugin
# is present we re-exec pytest once with it scrubbed from the environment.
def _restore_captured_stdio() -> None:
    """pytest's fd-level capture points fd 1/2 at throwaway tmpfiles by the
    time conftest imports, keeping dups of the real stdout/stderr at higher
    fds. The exec'd child would write into the doomed tmpfiles; find the
    saved originals and put them back on 1/2 first."""
    try:
        if os.fstat(1).st_nlink != 0:  # fd1 not a deleted capture tmpfile
            return
    except OSError:
        return
    saved = []
    for fd in range(3, 64):
        try:
            st = os.fstat(fd)
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue
        if st.st_nlink == 0 or target.startswith("socket:") or target == "/dev/null":
            continue
        saved.append(fd)
        if len(saved) == 2:
            break
    if len(saved) == 2:  # capture saves stdout first, then stderr
        os.dup2(saved[0], 1)
        os.dup2(saved[1], 2)


_MARKER = "CERBOS_TPU_TESTS_REEXECED"
if (
    _MARKER not in os.environ
    and any(".axon_site" in p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep))
):
    env = dict(os.environ)
    env[_MARKER] = "1"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p and ".axon_site" not in p
    ) or os.getcwd()
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    _restore_captured_stdio()
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *sys.argv[1:]], env)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
