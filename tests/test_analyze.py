"""Static policy analyzer: eligibility classes, lints, graph findings, and
the static↔runtime self-check over the golden + bench corpora.

The self-check is the analyzer's soundness contract (ISSUE 14 acceptance):

* zero ``device``-classed rules carry an oracle-routed kernel at lowering,
* every condition-driven oracle fallback the packer takes at runtime was
  predicted ``tagged-fallback`` or ``oracle-only`` — capacity overflow
  (roles > K, scope chains > D) is a sizing event, not a condition verdict,
  and is excluded explicitly.
"""

from __future__ import annotations

import json
import math
import sys

import pytest

import cerbos_tpu.namer as namer
from cerbos_tpu.cel import ast as A
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.tpu import TpuEvaluator
from cerbos_tpu.tpu.analyze import (
    CLASS_DEVICE,
    CLASS_ORACLE,
    CLASS_TAGGED,
    AnalysisReport,
    analyze_policies,
    analyze_table,
    expr_offset,
    publish,
    render_text,
)
from cerbos_tpu.tpu.columns import encode_value
from cerbos_tpu.tpu.lowering import lower_table
from cerbos_tpu.tpu.packer import _MISSING_SENTINEL


def table_for(src: str):
    return build_rule_table(compile_policy_set(list(parse_policies(src))))


MIXED_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: "default"
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.n > 5
    - actions: ["edit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.owner == P.id
    - actions: ["audit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: '"admin" in runtime.effectiveDerivedRoles'
"""


class TestEligibility:
    def test_three_classes(self):
        rt = table_for(MIXED_POLICY)
        rep = analyze_table(rt)
        by_action = {r.evaluation_key.rsplit("#", 1)[0] + "/" + str(r.rule_index): r for r in rep.rules}
        classes = [r.eligibility for r in sorted(rep.rules, key=lambda r: r.rule_index)]
        assert classes == [CLASS_DEVICE, CLASS_TAGGED, CLASS_ORACLE]
        assert len(by_action) == 3

    def test_tagged_fallback_carries_paths_and_tags(self):
        rep = analyze_table(table_for(MIXED_POLICY))
        tagged = next(r for r in rep.rules if r.eligibility == CLASS_TAGGED)
        paths = {fb["path"] for fb in tagged.fallbacks}
        assert paths == {"resource.attr.owner", "principal.id"}
        for fb in tagged.fallbacks:
            assert "other" in fb["tags"]
            assert fb["reasons"] == ["eq_collection_operand"]

    def test_oracle_only_reason_and_offset(self):
        rep = analyze_table(table_for(MIXED_POLICY))
        oracle = next(r for r in rep.rules if r.eligibility == CLASS_ORACLE)
        assert len(oracle.reasons) == 1
        reason = oracle.reasons[0]
        assert reason["code"] == "operand_unsupported"
        src = reason["expr"]
        assert "runtime.effectiveDerivedRoles" in src
        # the offset points at the offending token inside the expression
        assert reason["offset"] == src.index("effectiveDerivedRoles")

    def test_device_rules_keep_predicate_audit(self):
        rep = analyze_table(
            table_for(
                MIXED_POLICY.replace(
                    "R.attr.n > 5", 'startsWith(R.attr.name, "a")'
                )
            )
        )
        first = next(r for r in rep.rules if r.rule_index == 0)
        assert first.eligibility == CLASS_DEVICE
        assert [p["code"] for p in first.predicates] == ["unsupported_function"]
        assert first.predicates[0]["offset"] == first.predicates[0]["expr"].index("startsWith")

    def test_summary_and_json_roundtrip(self):
        rep = analyze_table(table_for(MIXED_POLICY))
        d = json.loads(json.dumps(rep.to_dict(), default=str))
        assert d["summary"]["classes"] == {CLASS_DEVICE: 1, CLASS_TAGGED: 1, CLASS_ORACLE: 1}
        assert len(d["rules"]) == 3
        assert "policy analysis: 3 rules" in rep.summary_line()
        assert rep.failed("oracle-only") is True
        assert "oracle-only" in render_text(rep)


LINT_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: lint_target
  version: "default"
  variables:
    local:
      v1: R.attr.a
      v2: V.v1 && V.v1
      v3: V.v2 && V.v2
      v4: V.v3 && V.v3
      v5: V.v4 && V.v4
      v6: V.v5 && V.v5
      v7: V.v6 && V.v6
      v8: V.v7 && V.v7
      v9: V.v8 && V.v8
  rules:
    - actions: ["a"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.score == 0.3
    - actions: ["b"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.name < "m"
    - actions: ["c"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.created) < R.attr.deadline
    - actions: ["d"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: V.v9
"""


class TestDivergenceLints:
    @pytest.fixture(scope="class")
    def report(self):
        return analyze_table(table_for(LINT_POLICY))

    def _codes(self, report):
        return {f.code for f in report.findings if f.kind == "divergence-risk"}

    def test_float_equality(self, report):
        f = next(f for f in report.findings if f.code == "float_equality")
        assert f.rule_index == 0
        assert f.offset == f.expr.index("==")

    def test_string_ordering(self, report):
        f = next(f for f in report.findings if f.code == "string_ordering")
        assert f.rule_index == 1

    def test_mixed_timestamp(self, report):
        f = next(f for f in report.findings if f.code == "mixed_timestamp_comparison")
        assert f.rule_index == 2

    def test_deep_inlining(self, report):
        deep = [f for f in report.findings if f.code == "deep_inlining"]
        # v8 (depth 8) and v9 (depth 9) both cross DEEP_INLINE_WARN; v7 doesn't
        assert sorted(f.message.split("'")[1] for f in deep) == ["v8", "v9"]

    def test_nan_constant_lint(self):
        # CEL has no NaN literal; the lint guards constants injected via
        # YAML (`.nan`) and future AST producers — drive it directly
        from cerbos_tpu.tpu.analyze import _lint_expr

        node = A.Call(fn="_==_", args=(A.Select(A.Ident("R"), "x"), A.Lit(math.nan)))
        hits = []
        _lint_expr("R.x == nan", node, lambda code, msg, src, n: hits.append(code))
        assert "nan_constant" in hits


DEAD_RULE_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: graveyard
  version: "default"
  rules:
    - actions: ["write"]
      effect: EFFECT_DENY
      roles: ["*"]
    - actions: ["write"]
      effect: EFFECT_ALLOW
      roles: [editor]
      condition:
        match:
          expr: R.attr.n == 1
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [viewer]
"""

UNREACHABLE_DR_POLICY = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: dr_pack
  definitions:
    - name: used_role
      parentRoles: [user]
      condition:
        match:
          expr: R.attr.owner == P.id
    - name: unused_role
      parentRoles: [user]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: dr_target
  version: "default"
  importDerivedRoles: [dr_pack]
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      derivedRoles: [used_role]
"""


class TestGraphFindings:
    def test_dead_rule(self):
        rep = analyze_table(table_for(DEAD_RULE_POLICY))
        dead = [f for f in rep.findings if f.code == "dead_rule"]
        assert len(dead) == 1
        assert "write" in dead[0].message
        # the surviving read/viewer ALLOW is not flagged
        assert "read" not in dead[0].message

    def test_unreachable_derived_role(self):
        # the compiler prunes unused definitions before the rule table, so
        # the finding is only produced by the raw-policy entry point
        rep = analyze_policies(list(parse_policies(UNREACHABLE_DR_POLICY)))
        unreachable = [f for f in rep.findings if f.code == "unreachable_derived_role"]
        assert len(unreachable) == 1
        assert "unused_role" in unreachable[0].message
        assert "used_role" not in unreachable[0].message.replace("unused_role", "")

    def test_undefined_global_reference(self):
        rep = analyze_table(
            table_for(MIXED_POLICY.replace("R.attr.n > 5", 'G.missing == "x"'))
        )
        undef = [f for f in rep.findings if f.code == "undefined_reference"]
        assert len(undef) == 1
        assert undef[0].severity == "error"
        assert "missing" in undef[0].message
        # and the rule itself went oracle-only with the matching reason code
        r0 = next(r for r in rep.rules if r.rule_index == 0)
        assert r0.eligibility == CLASS_ORACLE
        assert r0.reasons[0]["code"] == "undefined_global"

    def test_defined_global_is_clean(self):
        rep = analyze_table(
            table_for(MIXED_POLICY.replace("R.attr.n > 5", 'G.env == "prod"')),
            globals_={"env": "prod"},
        )
        assert not [f for f in rep.findings if f.code == "undefined_reference"]
        r0 = next(r for r in rep.rules if r.rule_index == 0)
        assert r0.eligibility == CLASS_DEVICE


class TestPublish:
    def test_gauges_and_stale_zeroing(self):
        from cerbos_tpu.observability import metrics

        vec_name = "cerbos_tpu_policy_analysis_total"
        publish(analyze_table(table_for(MIXED_POLICY)))
        vec = metrics().instruments()[vec_name]
        assert vec.get((CLASS_ORACLE, "operand_unsupported")) == 1.0
        assert vec.get((CLASS_TAGGED, "eq_collection_operand")) == 1.0
        # republish with a device-only table: the vanished keys read 0, not
        # their stale values
        device_only = MIXED_POLICY.split("    - actions: [\"edit\"]")[0]
        publish(analyze_table(table_for(device_only)))
        assert vec.get((CLASS_ORACLE, "operand_unsupported")) == 0.0
        assert vec.get((CLASS_TAGGED, "eq_collection_operand")) == 0.0
        assert vec.get((CLASS_DEVICE, "ok")) == 1.0

    def test_latest_retained(self):
        from cerbos_tpu.tpu import analyze as analyze_mod

        rep = publish(analyze_table(table_for(MIXED_POLICY)))
        assert analyze_mod.latest() is rep


class TestAnalyzePolicies:
    def test_compiles_raw_policy_objects(self):
        rep = analyze_policies(list(parse_policies(MIXED_POLICY)))
        assert isinstance(rep, AnalysisReport)
        assert len(rep.rules) == 3


# ---------------------------------------------------------------------------
# static ↔ runtime self-check


def _assert_static_agreement(rt, globals_=None):
    """oracle-only ⟺ needs_oracle, per rule; device ⇒ clean kernels."""
    lt = lower_table(rt, globals_ or {})
    rep = analyze_table(rt, globals_ or {}, lowered=lt)
    assert rep.rules, "corpus produced no rules"
    for rule in rep.rules:
        lr = lt.rows[rule.row_id]
        assert (rule.eligibility == CLASS_ORACLE) == lr.needs_oracle, (
            f"{rule.policy} rule#{rule.rule_index}: class {rule.eligibility} "
            f"vs needs_oracle={lr.needs_oracle}"
        )
        kernels = [
            lt.compiler.kernels[c]
            for c in (lr.cond_id, lr.drcond_id, lr.negated_cond_id)
            if c >= 0
        ]
        if rule.eligibility == CLASS_DEVICE:
            assert all(k.emit is not None for k in kernels)
            assert not any(k.fallback_tags for k in kernels)
    return lt, rep


class TestSelfCheckStatic:
    def test_golden_corpus(self):
        sys.path.insert(0, __file__.rsplit("/", 1)[0])
        from golden_loader import GOLDEN_GLOBALS, golden_policies

        _store, compiled = golden_policies()
        rt = build_rule_table(compiled)
        lt, rep = _assert_static_agreement(rt, GOLDEN_GLOBALS)
        # the golden store intentionally contains every class
        counts = rep.class_counts()
        assert counts[CLASS_DEVICE] > 0
        assert counts[CLASS_TAGGED] > 0

    @pytest.mark.slow
    def test_bench_corpus(self):
        from cerbos_tpu.util.bench_corpus import corpus_yaml

        rt = table_for(corpus_yaml(40))
        _assert_static_agreement(rt)

    def test_bench_corpus_small(self):
        from cerbos_tpu.util.bench_corpus import corpus_yaml

        rt = table_for(corpus_yaml(8))
        _assert_static_agreement(rt)


SELFCHECK_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: "default"
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.owner == P.id
    - actions: ["audit"]
      effect: EFFECT_ALLOW
      roles: [auditor]
      condition:
        match:
          expr: '"admin" in runtime.effectiveDerivedRoles'
    - actions: ["list"]
      effect: EFFECT_ALLOW
      roles: ["*"]
"""


def _explain_oracle_plans(ev, rep, inputs, params):
    """Every plan.oracle the packer produced must be capacity-driven or
    predicted by the analyzer. Returns the observed (tagged, cell) counts."""
    packer = ev.packer
    lt = ev.lowered
    rt = lt.table
    oracle_rules = {r.row_id for r in rep.rules if r.eligibility == CLASS_ORACLE}
    tagged_paths = {
        fb["path"] for r in rep.rules if r.eligibility == CLASS_TAGGED for fb in r.fallbacks
    }
    batch = packer.pack(inputs, params)
    n_tagged = n_cell = 0
    for plan in batch.plans:
        if not plan.oracle:
            continue
        inp = plan.input
        # 1. capacity overflow: not a condition verdict, excluded
        if (
            len(plan.roles) > packer.K
            or len(plan.principal_scopes) > packer.D
            or len(plan.resource_scopes) > packer.D
        ):
            continue
        # 2. fallback-tag trigger: a value at a registered path carries a
        #    routed tag — must have been predicted tagged-fallback
        triggered = False
        for path, tags in lt.fallback_tags.items():
            v = packer._path_accessor(path)(inp)
            if v is _MISSING_SENTINEL:
                continue
            try:
                tag = encode_value(v, True, lt.interner)[0]
            except Exception:
                continue
            if tag in tags:
                assert ".".join(path) in tagged_paths, (
                    f"runtime fallback at {path} not predicted tagged-fallback"
                )
                triggered = True
        if triggered:
            n_tagged += 1
            continue
        # 3. cell-driven: a candidate row needs the oracle — must have been
        #    predicted oracle-only
        sanitized = namer.sanitize(inp.resource.kind)
        version = inp.resource.policy_version or params.default_policy_version or "default"
        rscope = inp.resource.scope
        pid = inp.principal.id if inp.principal.id in rt.idx.principal else ""
        from cerbos_tpu.ruletable.rows import KIND_PRINCIPAL, KIND_RESOURCE

        needy = set()
        parent_roles = rt.idx.add_parent_roles([rscope], plan.roles)
        for kind, chain, qpid in (
            (KIND_PRINCIPAL, tuple(plan.principal_scopes), pid),
            (KIND_RESOURCE, tuple(plan.resource_scopes), ""),
        ):
            if kind == KIND_PRINCIPAL and not qpid:
                continue
            for action in inp.actions:
                for scope in chain:
                    for r in rt.idx.query(version, sanitized, scope, action, parent_roles, kind, qpid):
                        lr = lt.rows.get(r.id)
                        if lr is not None and lr.needs_oracle:
                            needy.add(r.id)
        assert needy, f"unexplained oracle fallback for input {inp}"
        assert needy & oracle_rules, (
            f"needs_oracle rows {needy} not predicted oracle-only ({oracle_rules})"
        )
        n_cell += 1
    return n_tagged, n_cell


class TestSelfCheckRuntime:
    def test_condition_driven_fallbacks_predicted(self):
        rt = table_for(SELFCHECK_POLICY)
        params = EvalParams()
        ev = TpuEvaluator(rt, use_jax=False, min_device_batch=0)
        rep = analyze_table(rt, lowered=ev.lowered)
        inputs = [
            # scalar owner: device-served
            CheckInput(
                request_id="r0",
                principal=Principal(id="u1", roles=["user"], attr={}),
                resource=Resource(kind="doc", id="d0", attr={"owner": "u1"}),
                actions=["read"],
            ),
            # list owner: fallback tag (other) at resource.attr.owner
            CheckInput(
                request_id="r1",
                principal=Principal(id="u1", roles=["user"], attr={}),
                resource=Resource(kind="doc", id="d1", attr={"owner": ["u1", "u2"]}),
                actions=["read"],
            ),
            # oracle-only rule in the audit cell
            CheckInput(
                request_id="r2",
                principal=Principal(id="u2", roles=["auditor"], attr={}),
                resource=Resource(kind="doc", id="d2", attr={}),
                actions=["audit"],
            ),
        ]
        n_tagged, n_cell = _explain_oracle_plans(ev, rep, inputs, params)
        assert n_tagged >= 1, "list-valued owner should trigger a tagged fallback"
        assert n_cell >= 1, "audit action should route through the oracle-only cell"

    def test_golden_corpus_runtime(self):
        sys.path.insert(0, __file__.rsplit("/", 1)[0])
        from golden_loader import GOLDEN_GLOBALS, golden_policies

        import test_engine_check as corpus

        _store, compiled = golden_policies()
        rt = build_rule_table(compiled)
        params = EvalParams(globals=dict(GOLDEN_GLOBALS))
        ev = TpuEvaluator(rt, globals_=params.globals, use_jax=False, min_device_batch=0)
        rep = analyze_table(rt, params.globals, lowered=ev.lowered)
        P, R = corpus.P, corpus.R
        inputs = [
            CheckInput(
                request_id=f"g{i}",
                principal=P(id=pid, roles=roles, attr=pattr),
                resource=R(kind=kind, attr=rattr),
                actions=actions,
            )
            for i, (pid, roles, pattr, kind, rattr, actions) in enumerate(
                [
                    ("john", ["employee"], {"department": "marketing", "geography": "GB", "team": "design"}, "leave_request", {"department": "marketing", "geography": "GB", "id": "XX125", "owner": "john", "team": "design"}, ["view:public", "approve", "defer"]),
                    ("bev", ["employee", "manager"], {"department": "marketing", "geography": "GB", "managed_geographies": "GB", "team": "design"}, "leave_request", {"department": "marketing", "geography": "GB", "id": "XX125", "owner": "john", "status": "PENDING_APPROVAL", "team": "design"}, ["view:public", "approve"]),
                    ("donald_duck", ["employee"], {"department": "engineering", "geography": "EU", "team": "QA"}, "equipment_request", {"department": "engineering", "geography": "EU", "id": "XX150", "owner": "daffy_duck", "team": "QA"}, ["view:public", "approve"]),
                ]
            )
        ]
        _explain_oracle_plans(ev, rep, inputs, params)

    def test_bench_corpus_runtime(self):
        from cerbos_tpu.util.bench_corpus import corpus_yaml, requests

        rt = table_for(corpus_yaml(8))
        params = EvalParams()
        ev = TpuEvaluator(rt, use_jax=False, min_device_batch=0)
        rep = analyze_table(rt, lowered=ev.lowered)
        _explain_oracle_plans(ev, rep, requests(64, 8, seed=11), params)


class TestExprOffset:
    def test_operator_and_literal_anchors(self):
        from cerbos_tpu.cel.parser import parse

        src = 'R.attr.x == "hello"'
        node = parse(src)
        assert expr_offset(src, node) == src.index("==")
        assert expr_offset(src, node.args[1]) == src.index('"hello"')
        assert expr_offset(src, node.args[0]) == src.index("x")

    def test_unknown_node_is_minus_one(self):
        assert expr_offset("R.attr.x == 1", None) == -1


class TestCtlAnalyze:
    """`cerbos-tpuctl analyze` exit-code contract (CI gating)."""

    def _run(self, capsys, *argv):
        from cerbos_tpu.ctl import main

        rc = main(["analyze", *argv])
        out = capsys.readouterr()
        return rc, out.out, out.err

    def test_quickstart_passes_oracle_gate(self, capsys):
        rc, out, _err = self._run(
            capsys, "examples/quickstart", "--fail-on", "oracle-only"
        )
        assert rc == 0
        assert "policy analysis" in out or "rules" in out

    def test_fixture_with_uncompilable_condition_fails_gate(self, capsys, tmp_path):
        fixture = tmp_path / "oracle.yaml"
        fixture.write_text(SELFCHECK_POLICY)
        rc, _out, err = self._run(
            capsys, str(fixture), "--fail-on", "oracle-only"
        )
        assert rc == 1
        assert "oracle-only" in err

    def test_no_gate_reports_and_exits_zero(self, capsys, tmp_path):
        fixture = tmp_path / "oracle.yaml"
        fixture.write_text(SELFCHECK_POLICY)
        rc, out, _err = self._run(capsys, str(fixture))
        assert rc == 0
        assert "oracle-only" in out

    def test_json_output_is_parseable(self, capsys, tmp_path):
        fixture = tmp_path / "oracle.yaml"
        fixture.write_text(SELFCHECK_POLICY)
        rc, out, _err = self._run(
            capsys, str(fixture), "--json", "--fail-on", "divergence-risk"
        )
        assert rc == 0
        d = json.loads(out)
        assert {r["eligibility"] for r in d["rules"]} == {
            CLASS_DEVICE, CLASS_TAGGED, CLASS_ORACLE
        }

    def test_compile_error_exits_three(self, capsys, tmp_path):
        fixture = tmp_path / "broken.yaml"
        fixture.write_text(UNREACHABLE_DR_POLICY.split("---")[1])
        rc, _out, err = self._run(capsys, str(fixture))
        assert rc == 3
        assert "ERROR" in err
