"""S3 blob transport: SigV4 known-answer vector + fake-server sync tests."""

import datetime
import hashlib
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from cerbos_tpu.storage.blob import BlobStore
from cerbos_tpu.storage.s3 import S3Client, sigv4_headers


def test_sigv4_known_answer_vector():
    """AWS's published SigV4 example (docs: 'Signature calculation examples',
    GET iam ListUsers): signing key AKIDEXAMPLE/wJalr..., 2015-08-30T12:36Z,
    us-east-1/iam — expected signature
    5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7."""
    headers = sigv4_headers(
        "GET",
        "https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
        region="us-east-1",
        service="iam",
        access_key="AKIDEXAMPLE",
        secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        now=datetime.datetime(2015, 8, 30, 12, 36, 0, tzinfo=datetime.timezone.utc),
        extra_headers={"content-type": "application/x-www-form-urlencoded; charset=utf-8"},
    )
    auth = headers["Authorization"]
    assert "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request" in auth
    assert "SignedHeaders=content-type;host;x-amz-date" in auth
    assert auth.endswith("Signature=5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7")


POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


class _FakeS3:
    """Path-style S3 server: ListObjectsV2 (with pagination) + GetObject.
    Rejects requests whose SigV4 Authorization header is missing/mis-scoped."""

    def __init__(self, bucket="policies", page_size=2):
        self.bucket = bucket
        self.objects: dict[str, bytes] = {}
        self.page_size = page_size
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 Credential=") or "Signature=" not in auth:
                    self.send_error(403, "SignatureDoesNotMatch")
                    return
                if self.headers.get("x-amz-content-sha256") is None:
                    self.send_error(403, "MissingContentSha256")
                    return
                parsed = urllib.parse.urlsplit(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                if parts[0] != outer.bucket:
                    self.send_error(404, "NoSuchBucket")
                    return
                qs = dict(urllib.parse.parse_qsl(parsed.query))
                if len(parts) == 1 or not parts[1]:
                    self._list(qs)
                    return
                key = urllib.parse.unquote(parts[1])
                body = outer.objects.get(key)
                if body is None:
                    self.send_error(404, "NoSuchKey")
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _list(self, qs):
                assert qs.get("list-type") == "2"
                prefix = qs.get("prefix", "")
                keys = sorted(k for k in outer.objects if k.startswith(prefix))
                start = int(qs.get("continuation-token", "0"))
                page = keys[start : start + outer.page_size]
                truncated = start + outer.page_size < len(keys)
                items = "".join(
                    f"<Contents><Key>{k}</Key>"
                    f"<ETag>&quot;{hashlib.md5(outer.objects[k]).hexdigest()}&quot;</ETag>"
                    f"<Size>{len(outer.objects[k])}</Size></Contents>"
                    for k in page
                )
                nxt = f"<NextContinuationToken>{start + outer.page_size}</NextContinuationToken>" if truncated else ""
                body = (
                    '<?xml version="1.0"?><ListBucketResult xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                    f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>{items}{nxt}"
                    "</ListBucketResult>"
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


@pytest.fixture()
def fake_s3():
    srv = _FakeS3()
    srv.objects["policies/doc.yaml"] = POLICY.encode()
    srv.objects["policies/_schemas/doc.json"] = b'{"type": "object"}'
    srv.objects["other/ignored.yaml"] = b"not: synced"
    yield srv
    srv.stop()


def _client(srv):
    return S3Client(
        bucket=srv.bucket,
        endpoint_url=f"http://127.0.0.1:{srv.port}",
        access_key="test-access",
        secret_key="test-secret",
    )


def test_list_and_get(fake_s3):
    c = _client(fake_s3)
    objs = c.list_objects("policies/")
    assert [o.key for o in objs] == ["policies/_schemas/doc.json", "policies/doc.yaml"]
    assert c.get_object("policies/doc.yaml") == POLICY.encode()


def test_list_pagination(fake_s3):
    # 3 objects, page size 2 → continuation token exercised
    assert len(_client(fake_s3).list_objects()) == 3


def test_keys_needing_percent_encoding(fake_s3):
    """S3's encode-once rule: keys with spaces/unicode must sign over the
    path AS SENT, not a re-encoded (double-encoded) form."""
    fake_s3.objects["policies/a b ü.yaml"] = b"data: 1"
    c = _client(fake_s3)
    assert c.get_object("policies/a b ü.yaml") == b"data: 1"
    keys = [o.key for o in c.list_objects("policies/")]
    assert "policies/a b ü.yaml" in keys


def test_blob_store_syncs_from_s3(fake_s3, tmp_path):
    store = BlobStore(
        bucket_url=f"s3://{fake_s3.bucket}",
        work_dir=str(tmp_path / "clone"),
        update_poll_interval=0,
        endpoint_url=f"http://127.0.0.1:{fake_s3.port}",
        prefix="policies/",
        access_key="test-access",
        secret_key="test-secret",
    )
    assert len(store.get_all()) == 1
    assert store.get_schema("doc.json") == b'{"type": "object"}'

    # object changes + deletion propagate on the next sync
    fake_s3.objects["policies/doc.yaml"] = POLICY.replace('["view"]', '["view","edit"]').encode()
    del fake_s3.objects["policies/_schemas/doc.json"]
    events = store.sync_and_compare()
    assert events, "changed bucket must emit storage events"
    assert store.get_schema("doc.json") is None
    store.close()


def test_unsigned_request_rejected(fake_s3):
    import urllib.request

    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(f"http://127.0.0.1:{fake_s3.port}/{fake_s3.bucket}?list-type=2")
