"""Multi-process worker pool: SO_REUSEPORT serving, crash restart, shutdown.

Boots the real CLI (``cerbos_tpu.cli server --workers 2``) as a subprocess —
the same entry a production pool uses — and drives it over HTTP.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""

CHECK_BODY = {
    "requestId": "w1",
    "principal": {"id": "alice", "roles": ["user"]},
    "resources": [
        {"actions": ["view", "delete"], "resource": {"kind": "album", "id": "a1", "attr": {"public": True}}}
    ],
}


def _check(port: int, timeout: float = 5.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/check/resources",
        data=json.dumps(CHECK_BODY).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _worker_pids(pool_pid: int) -> list[int]:
    out = subprocess.run(
        ["ps", "-o", "pid=", "--ppid", str(pool_pid)], capture_output=True, text=True
    )
    return [int(p) for p in out.stdout.split()]


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    policy_dir = tmp_path_factory.mktemp("policies")
    (policy_dir / "album.yaml").write_text(POLICY)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "cerbos_tpu.cli", "server",
            "--workers", "2",
            "--set", f"storage.disk.directory={policy_dir}",
            "--set", "server.httpListenAddr=127.0.0.1:0",
            "--set", "server.grpcListenAddr=127.0.0.1:0",
            "--set", "engine.tpu.enabled=false",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )
    http_port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("cerbos-tpu serving:"):
            for tok in line.split():
                if tok.startswith("http="):
                    http_port = int(tok.split("=")[1])
            break
    assert http_port, "pool never announced its ports"
    # wait until a worker actually serves
    deadline = time.time() + 60
    last_err = None
    while time.time() < deadline:
        try:
            _check(http_port)
            break
        except Exception as e:  # noqa: BLE001
            last_err = e
            time.sleep(0.25)
    else:
        proc.terminate()
        raise AssertionError(f"pool never became ready: {last_err}")
    yield proc, http_port
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=15)


def test_pool_serves_decisions(pool):
    proc, port = pool
    for _ in range(10):
        resp = _check(port)
        actions = resp["results"][0]["actions"]
        assert actions["view"] == "EFFECT_ALLOW"
        assert actions["delete"] == "EFFECT_DENY"


def test_pool_has_n_workers(pool):
    proc, port = pool
    assert len(_worker_pids(proc.pid)) == 2


def test_pool_restarts_crashed_worker(pool):
    proc, port = pool
    before = _worker_pids(proc.pid)
    os.kill(before[0], signal.SIGKILL)
    deadline = time.time() + 30
    while time.time() < deadline:
        pids = _worker_pids(proc.pid)
        if len(pids) == 2 and pids != before:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("killed worker was not replaced")
    # the pool keeps serving throughout (the surviving worker + the new one)
    resp = _check(port)
    assert resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW"


def test_pool_shuts_down_cleanly(pool):
    proc, port = pool
    proc.terminate()
    assert proc.wait(timeout=20) == 0


# -- multi-process front door: N front ends + 1 shared batcher ---------------


def _get(port: int, path: str, timeout: float = 5.0):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _batcher_pid(port: int) -> int:
    """The batcher process self-identifies through the routed flight dump."""
    status, body = _get(port, "/_cerbos/debug/flight")
    assert status == 200
    doc = json.loads(body)
    assert doc.get("source") == "batcher", doc
    return int(doc["batcher_pid"])


@pytest.fixture(scope="module")
def frontdoor(tmp_path_factory):
    """Real CLI boot of the PR 6 topology: 2 HTTP front-end processes feeding
    one shared batcher process over the unix ticket queue (numpy device
    backend so the subprocess boots fast and jax-free)."""
    policy_dir = tmp_path_factory.mktemp("policies")
    (policy_dir / "album.yaml").write_text(POLICY)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "cerbos_tpu.cli", "server",
            "--frontends", "2",
            "--set", f"storage.disk.directory={policy_dir}",
            "--set", "server.httpListenAddr=127.0.0.1:0",
            "--set", "server.grpcListenAddr=127.0.0.1:0",
            "--set", "engine.tpu.backend=numpy",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )
    http_port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("cerbos-tpu serving:"):
            for tok in line.split():
                if tok.startswith("http="):
                    http_port = int(tok.split("=")[1])
            break
    assert http_port, "front door never announced its ports"
    deadline = time.time() + 60
    last_err = None
    while time.time() < deadline:
        try:
            _check(http_port)
            break
        except Exception as e:  # noqa: BLE001
            last_err = e
            time.sleep(0.25)
    else:
        proc.terminate()
        raise AssertionError(f"front door never became ready: {last_err}")
    yield proc, http_port
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=15)


def test_frontdoor_serves_decisions(frontdoor):
    proc, port = frontdoor
    for _ in range(10):
        resp = _check(port)
        actions = resp["results"][0]["actions"]
        assert actions["view"] == "EFFECT_ALLOW"
        assert actions["delete"] == "EFFECT_DENY"


def test_frontdoor_topology(frontdoor):
    proc, port = frontdoor
    # 2 front ends + 1 batcher
    assert len(_worker_pids(proc.pid)) == 3
    assert _batcher_pid(port) in _worker_pids(proc.pid)


def test_frontdoor_ready_and_worker_labeled_metrics(frontdoor):
    proc, port = frontdoor
    status, body = _get(port, "/_cerbos/ready")
    assert status == 200
    assert json.loads(body)["status"] in ("ready", "degraded")
    # one scrape sees this front end's series AND the batcher process's
    # (ipc queue depth et al), each stamped with its worker identity
    _check(port)
    status, body = _get(port, "/_cerbos/metrics")
    assert status == 200
    text = body.decode()
    assert 'worker="fe' in text
    assert 'worker="batcher"' in text
    assert "cerbos_tpu_ipc_ring_depth" in text
    # the pool's HELLO negotiation granted the shm data plane (the native
    # module is built in this image); the SIGKILL chaos test below therefore
    # exercises the ring transport, not the uds fallback
    from cerbos_tpu import native

    if native.get() is not None:
        assert 'transport="shm"' in text, "front door did not grant shm"


def test_frontdoor_batcher_sigkill_midload_loses_zero_requests(frontdoor):
    """The PR's chaos acceptance: SIGKILL the batcher process under live
    traffic — every request settles (front ends fall back to their
    COW-shared oracle), readiness stays live, the supervisor respawns the
    batcher, and the ticket queue re-attaches."""
    proc, port = frontdoor
    victim = _batcher_pid(port)
    results = {"ok": 0, "bad": []}
    stop_at = time.time() + 6.0

    def hammer():
        while time.time() < stop_at:
            try:
                resp = _check(port, timeout=10.0)
                if resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW":
                    results["ok"] += 1
                else:
                    results["bad"].append(resp)
            except Exception as e:  # noqa: BLE001
                results["bad"].append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    os.kill(victim, signal.SIGKILL)
    # while the batcher is down/respawning, front ends stay live (degraded
    # serves from the oracle) — readiness must NOT flip back to 503
    status, body = _get(port, "/_cerbos/ready")
    assert status == 200
    for t in threads:
        t.join(timeout=30.0)
    assert not results["bad"], f"lost/failed requests: {results['bad'][:5]}"
    assert results["ok"] > 0
    # the supervisor replaced the batcher and the queue re-attached
    deadline = time.time() + 30
    new_pid = None
    while time.time() < deadline:
        try:
            new_pid = _batcher_pid(port)
            if new_pid != victim:
                break
        except AssertionError:
            pass
        time.sleep(0.5)
    assert new_pid is not None and new_pid != victim, "batcher was not respawned"
    status, body = _get(port, "/_cerbos/ready")
    assert status == 200
