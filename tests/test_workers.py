"""Multi-process worker pool: SO_REUSEPORT serving, crash restart, shutdown.

Boots the real CLI (``cerbos_tpu.cli server --workers 2``) as a subprocess —
the same entry a production pool uses — and drives it over HTTP.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""

CHECK_BODY = {
    "requestId": "w1",
    "principal": {"id": "alice", "roles": ["user"]},
    "resources": [
        {"actions": ["view", "delete"], "resource": {"kind": "album", "id": "a1", "attr": {"public": True}}}
    ],
}


def _check(port: int, timeout: float = 5.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/check/resources",
        data=json.dumps(CHECK_BODY).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def _worker_pids(pool_pid: int) -> list[int]:
    out = subprocess.run(
        ["ps", "-o", "pid=", "--ppid", str(pool_pid)], capture_output=True, text=True
    )
    return [int(p) for p in out.stdout.split()]


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    policy_dir = tmp_path_factory.mktemp("policies")
    (policy_dir / "album.yaml").write_text(POLICY)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "cerbos_tpu.cli", "server",
            "--workers", "2",
            "--set", f"storage.disk.directory={policy_dir}",
            "--set", "server.httpListenAddr=127.0.0.1:0",
            "--set", "server.grpcListenAddr=127.0.0.1:0",
            "--set", "engine.tpu.enabled=false",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )
    http_port = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("cerbos-tpu serving:"):
            for tok in line.split():
                if tok.startswith("http="):
                    http_port = int(tok.split("=")[1])
            break
    assert http_port, "pool never announced its ports"
    # wait until a worker actually serves
    deadline = time.time() + 60
    last_err = None
    while time.time() < deadline:
        try:
            _check(http_port)
            break
        except Exception as e:  # noqa: BLE001
            last_err = e
            time.sleep(0.25)
    else:
        proc.terminate()
        raise AssertionError(f"pool never became ready: {last_err}")
    yield proc, http_port
    if proc.poll() is None:
        proc.terminate()
        proc.wait(timeout=15)


def test_pool_serves_decisions(pool):
    proc, port = pool
    for _ in range(10):
        resp = _check(port)
        actions = resp["results"][0]["actions"]
        assert actions["view"] == "EFFECT_ALLOW"
        assert actions["delete"] == "EFFECT_DENY"


def test_pool_has_n_workers(pool):
    proc, port = pool
    assert len(_worker_pids(proc.pid)) == 2


def test_pool_restarts_crashed_worker(pool):
    proc, port = pool
    before = _worker_pids(proc.pid)
    os.kill(before[0], signal.SIGKILL)
    deadline = time.time() + 30
    while time.time() < deadline:
        pids = _worker_pids(proc.pid)
        if len(pids) == 2 and pids != before:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("killed worker was not replaced")
    # the pool keeps serving throughout (the surviving worker + the new one)
    resp = _check(port)
    assert resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW"


def test_pool_shuts_down_cleanly(pool):
    proc, port = pool
    proc.terminate()
    assert proc.wait(timeout=20) == 0
