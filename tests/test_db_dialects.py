"""Statement-shape tests for the mysql/postgres dialects.

No mysql/postgres server exists in this environment, so the dialect SQL is
exercised through a recording fake DB-API connection: every statement the
store core executes is captured and checked for (a) placeholder/arg-count
agreement, (b) no un-rewritten '?' markers in %s dialects, (c) the exact
statement text (golden), so a typo in dialect SQL fails here instead of at
a customer's database (VERDICT r2 weak #7).
"""

import re

import pytest

from cerbos_tpu.storage.db import DBStore, MySQLDialect, PostgresDialect, Sqlite3Dialect

POLICY_DOC = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


class FakeCursor:
    def __init__(self, log):
        self.log = log
        self.rowcount = 0

    def execute(self, sql, args=()):
        self.log.append((sql, tuple(args)))

    def executemany(self, sql, seq):
        for args in seq:
            self.log.append((sql, tuple(args)))

    def fetchall(self):
        return []

    def fetchone(self):
        return None


class FakeConn:
    def __init__(self):
        self.statements = []

    def cursor(self):
        return FakeCursor(self.statements)

    def commit(self):
        pass

    def rollback(self):
        pass

    def close(self):
        pass


def _drive(dialect):
    """Run every store operation through a recording connection."""
    conn = FakeConn()
    dialect.connect = lambda conf: conn  # bypass the missing client library
    store = DBStore(dialect, {})
    store.get_all()
    store.get("cerbos.resource.doc.vdefault")
    store.get_schema("doc.json")
    store.list_schema_ids()
    store.add_or_update([POLICY_DOC])
    store.set_disabled(["cerbos.resource.doc.vdefault"], True)
    store.delete(["cerbos.resource.doc.vdefault"])
    store.list_policy_ids()
    store.list_policy_ids(include_disabled=True)
    store.get_raw("cerbos.resource.doc.vdefault")
    store.add_schema("doc.json", b"{}")
    store.delete_schema("doc.json")
    return conn.statements


@pytest.mark.parametrize("dialect_cls", [Sqlite3Dialect, MySQLDialect, PostgresDialect])
def test_placeholders_match_args(dialect_cls):
    dialect = dialect_cls()
    marker = dialect.placeholder
    for sql, args in _drive(dialect):
        if sql.strip().startswith("CREATE"):
            continue
        n = sql.count(marker)
        assert n == len(args), f"{dialect.name}: {n} markers vs {len(args)} args in: {sql}"
        if marker == "%s":
            assert "?" not in sql, f"{dialect.name}: un-rewritten '?' marker in: {sql}"


def _norm(sql: str) -> str:
    return re.sub(r"\s+", " ", sql).strip()


def test_mysql_statement_goldens():
    stmts = {_norm(s) for s, _ in _drive(MySQLDialect())}
    assert (
        "INSERT INTO policy (fqn, kind, definition, disabled) VALUES (%s, %s, %s, %s) "
        "ON DUPLICATE KEY UPDATE definition = VALUES(definition), kind = VALUES(kind), "
        "disabled = VALUES(disabled), updated_at = NOW()"
    ) in stmts
    assert (
        "INSERT INTO schema_defs (id, definition) VALUES (%s, %s) "
        "ON DUPLICATE KEY UPDATE definition = VALUES(definition)"
    ) in stmts
    assert "SELECT definition FROM policy WHERE disabled = %s" in stmts
    assert "DELETE FROM policy WHERE fqn = %s" in stmts
    # DDL uses MySQL column types
    ddl = " ".join(s for s, _ in _drive(MySQLDialect()) if s.strip().startswith("CREATE"))
    assert "MEDIUMTEXT" in ddl and "TINYINT" in ddl and "MEDIUMBLOB" in ddl


def test_postgres_statement_goldens():
    stmts = {_norm(s) for s, _ in _drive(PostgresDialect())}
    assert (
        "INSERT INTO policy (fqn, kind, definition, disabled) VALUES (%s, %s, %s, %s) "
        "ON CONFLICT(fqn) DO UPDATE SET definition = excluded.definition, "
        "kind = excluded.kind, disabled = excluded.disabled, updated_at = NOW()"
    ) in stmts
    assert (
        "INSERT INTO schema_defs (id, definition) VALUES (%s, %s) "
        "ON CONFLICT(id) DO UPDATE SET definition = excluded.definition"
    ) in stmts
    ddl = " ".join(s for s, _ in _drive(PostgresDialect()) if s.strip().startswith("CREATE"))
    assert "BOOLEAN" in ddl and "TIMESTAMPTZ" in ddl and "BYTEA" in ddl


def test_bool_column_representations():
    # postgres BOOLEAN must bind bool; mysql/sqlite TINYINT/INTEGER bind int
    assert PostgresDialect().bool_value(True) is True
    assert PostgresDialect().bool_value(False) is False
    assert MySQLDialect().bool_value(True) == 1
    assert Sqlite3Dialect().bool_value(False) == 0
