"""Decision provenance (ISSUE 20): device-side rule attribution end to end.

The contract: every ``ActionEffect`` names the rule-table row that won it
(``matched_rule``/``rule_row_id``) and the evaluator that produced it
(``source`` = device | oracle). The differential gate is the tentpole —
for every (resource, action) the device's winning rule must equal the CPU
oracle's, and must appear among the oracle tracer's ACTIVATED rules —
including principal-policy and scoped-policy wins. Around it: fallback
labeling under chaos, codec carriage on both IPC legs, sharded lane
attribution, the hot-rule recorder, includeMeta/audit surfacing, and the
parity sentinel's both-sides rule annotation rendered by
``cerbos-tpuctl replay-divergences --explain``.

The whole file must pass with and without the native codec
(``CERBOS_TPU_NO_NATIVE=1``) — the Makefile runs both legs.
"""

import json
import random

import pytest

from cerbos_tpu import native
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import types as T
from cerbos_tpu.engine.batcher import BatchingEvaluator
from cerbos_tpu.engine.faults import FaultInjector
from cerbos_tpu.engine.health import DeviceHealth
from cerbos_tpu.engine.hotrules import HotRuleRecorder
from cerbos_tpu.engine.ipc import decode_outputs, encode_outputs
from cerbos_tpu.engine.sentinel import DivergenceCorpus, ParitySentinel, provenance_rows
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu.evaluator import TpuEvaluator

pytestmark = pytest.mark.provenance

needs_native = pytest.mark.skipif(
    native.get() is None, reason="native module unavailable (CERBOS_TPU_NO_NATIVE?)"
)

# resource policy + scoped override + principal policy: the three win kinds
# the differential gate must attribute correctly
POLICIES = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: prov_roles
  definitions:
    - name: owner
      parentRoles: [viewer, editor]
      condition:
        match:
          expr: R.attr.owner == P.id
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: widget
  version: default
  importDerivedRoles: [prov_roles]
  rules:
    - name: read-any
      actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [viewer, editor]
    - name: write-owner
      actions: ["write"]
      effect: EFFECT_ALLOW
      derivedRoles: [owner]
    - name: purge-protected
      actions: ["purge"]
      effect: EFFECT_DENY
      roles: ["*"]
      condition:
        match:
          expr: R.attr.protected == true
    - name: purge-editor
      actions: ["purge"]
      effect: EFFECT_ALLOW
      roles: [editor]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: widget
  version: default
  scope: team
  rules:
    - name: team-read-deny
      actions: ["read"]
      effect: EFFECT_DENY
      roles: [viewer]
      condition:
        match:
          expr: R.attr.restricted == true
---
apiVersion: api.cerbos.dev/v1
principalPolicy:
  principal: special
  version: default
  rules:
    - resource: widget
      actions:
        - name: special-read
          action: "read"
          effect: EFFECT_ALLOW
        - name: special-purge
          action: "purge"
          effect: EFFECT_DENY
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICIES))))


@pytest.fixture()
def rt():
    return table()


def fuzz_inputs(n=120, seed=7):
    rng = random.Random(seed)
    inputs = []
    for i in range(n):
        roles = rng.sample(["viewer", "editor", "ghost"], k=rng.randint(1, 2))
        pid = rng.choice(["u1", "u2", "special"])
        attr = {}
        if rng.random() < 0.8:
            attr["owner"] = rng.choice(["u1", "u2"])
        if rng.random() < 0.5:
            attr["protected"] = rng.choice([True, False])
        if rng.random() < 0.4:
            attr["restricted"] = rng.choice([True, False])
        inputs.append(
            CheckInput(
                principal=Principal(id=pid, roles=roles),
                resource=Resource(
                    kind="widget", id=f"w{i}", attr=attr, scope=rng.choice(["", "team"])
                ),
                actions=rng.sample(["read", "write", "purge"], k=rng.randint(1, 3)),
                request_id=f"rq{i}",
            )
        )
    return inputs


def device(rt):
    return TpuEvaluator(rt, use_jax=False, min_device_batch=1)


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


# -- the differential gate ---------------------------------------------------


class TestDifferentialAttribution:
    def test_device_winning_rule_matches_oracle_everywhere(self, rt):
        """For every (resource, action): same effect, same winning rule FQN,
        same rule-table row id — across resource-policy, scoped-policy, and
        principal-policy wins."""
        inputs = fuzz_inputs()
        dev = device(rt).check(inputs, EvalParams())
        ora = oracle(rt, inputs)
        assert len(dev) == len(ora) == len(inputs)
        seen_kinds = set()
        for d, o in zip(dev, ora):
            assert set(d.actions) == set(o.actions)
            for a in d.actions:
                da, oa = d.actions[a], o.actions[a]
                ctx = f"{d.resource_id}/{a}"
                assert da.effect == oa.effect, ctx
                assert da.matched_rule == oa.matched_rule, ctx
                assert da.rule_row_id == oa.rule_row_id, ctx
                assert da.source == "device", ctx
                assert oa.source == "oracle", ctx
                if da.matched_rule.startswith("principal"):
                    seen_kinds.add("principal")
                elif "team" in da.matched_rule:
                    seen_kinds.add("scoped")
                elif da.matched_rule:
                    seen_kinds.add("resource")
        # the corpus genuinely exercised all three win kinds
        assert seen_kinds == {"principal", "scoped", "resource"}, seen_kinds

    def test_winning_rule_is_activated_in_the_tracer(self, rt):
        """The device's claimed rule must appear among the oracle tracer's
        ACTIVATED rules for that action — provenance is explainable, not
        just self-consistent."""
        from cerbos_tpu.tracer import traced_check

        inputs = fuzz_inputs(n=48, seed=11)
        dev = device(rt).check(inputs, EvalParams())
        checked = 0
        for i, d in zip(inputs, dev):
            _, rec = traced_check(rt, i, EvalParams())
            for a, ae in d.actions.items():
                if not ae.matched_rule or ae.matched_rule.startswith("principal"):
                    # the tracer walks resource-policy bindings only
                    continue
                activated = set()
                for e in rec.events:
                    if not e.activated:
                        continue
                    comps = {c["kind"]: c["id"] for c in e.components}
                    if comps.get("action") == a and "rule" in comps:
                        activated.add(f"{comps.get('policy')}#{comps['rule']}")
                assert ae.matched_rule in activated, (d.resource_id, a, ae.matched_rule, activated)
                checked += 1
        assert checked > 20  # the assertion actually ran

    def test_no_match_carries_no_rule(self, rt):
        out = device(rt).check(
            [
                CheckInput(
                    principal=Principal(id="x", roles=["ghost"]),
                    resource=Resource(kind="widget", id="w0"),
                    actions=["read"],
                )
            ],
            EvalParams(),
        )[0]
        ae = out.actions["read"]
        assert ae.effect == "EFFECT_DENY"
        assert ae.matched_rule == ""
        assert ae.rule_row_id == -1
        assert ae.source == "device"

    def test_bench_corpus_attribution_parity(self):
        """The golden corpus (the bench/loadtest workload) end to end."""
        from cerbos_tpu.util import bench_corpus

        rt = build_rule_table(
            compile_policy_set(list(parse_policies(bench_corpus.corpus_yaml(2))))
        )
        inputs = bench_corpus.requests(128, 2)
        dev = device(rt).check(inputs, EvalParams())
        ora = oracle(rt, inputs)
        for d, o in zip(dev, ora):
            for a in d.actions:
                assert d.actions[a].matched_rule == o.actions[a].matched_rule
                assert d.actions[a].rule_row_id == o.actions[a].rule_row_id


# -- oracle-fallback labeling under chaos ------------------------------------


class OracleEvaluator:
    def __init__(self, rt):
        self.rule_table = rt
        self.schema_mgr = None
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return oracle(self.rule_table, inputs, params)

    def submit(self, inputs, params=None):
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


def inp(i: int) -> CheckInput:
    return CheckInput(
        principal=Principal(id="u1", roles=["viewer"]),
        resource=Resource(kind="widget", id=f"w{i}", attr={"owner": "u1"}),
        actions=["read"],
        request_id=f"rq{i}",
    )


class TestFallbackLabeling:
    def test_breaker_open_fallback_is_labeled_oracle(self, rt):
        health = DeviceHealth(failure_threshold=1)
        b = BatchingEvaluator(device(rt), max_wait_ms=1.0, health=health)
        try:
            health.record_failure()  # breaker open: requests ride the oracle
            outs = b.check([inp(0), inp(1)])
            for o in outs:
                for ae in o.actions.values():
                    assert ae.source == "oracle"
                    assert ae.matched_rule  # attribution survives the fallback
        finally:
            b.close()

    def test_submit_crash_fallback_is_labeled_oracle(self, rt):
        """Chaos leg: the device path dies mid-flight; the batcher's oracle
        rescue must label its outputs honestly."""
        faulty = FaultInjector(device(rt), "submit_raise:1.0,seed:1")
        b = BatchingEvaluator(faulty, max_wait_ms=1.0)
        try:
            outs = b.check([inp(2)])
            assert outs[0].actions["read"].source == "oracle"
        finally:
            b.close()

    def test_device_path_is_labeled_device(self, rt):
        b = BatchingEvaluator(device(rt), max_wait_ms=1.0)
        try:
            outs = b.check([inp(3)])
            assert outs[0].actions["read"].source == "device"
        finally:
            b.close()


# -- codec carriage ----------------------------------------------------------


class TestCodecCarriage:
    def test_marshal_roundtrip_carries_provenance(self, rt):
        outs = oracle(rt, [inp(i) for i in range(4)])
        decoded = decode_outputs(encode_outputs(outs))
        for o, d in zip(outs, decoded):
            for a in o.actions:
                assert d.actions[a].matched_rule == o.actions[a].matched_rule
                assert d.actions[a].rule_row_id == o.actions[a].rule_row_id
                assert d.actions[a].source == o.actions[a].source

    @needs_native
    def test_native_reply_roundtrip_carries_provenance(self, rt):
        nat = native.get()
        outs = oracle(rt, [inp(i) for i in range(4)])
        assert any(ae.matched_rule for o in outs for ae in o.actions.values())
        frame = nat.reply_pack(outs, (0.001, [], "device", None, 0))
        decoded, _spec = nat.reply_unpack(
            frame, T.CheckOutput, T.ActionEffect, T.ValidationError, T.OutputEntry
        )
        for o, d in zip(outs, decoded):
            for a in o.actions:
                assert d.actions[a].matched_rule == o.actions[a].matched_rule
                assert d.actions[a].rule_row_id == o.actions[a].rule_row_id
                assert d.actions[a].source == o.actions[a].source

    def test_ipc_end_to_end_carries_provenance(self, rt, tmp_path):
        """Front-door topology: the winning rule crosses the ticket queue on
        whichever transport the pair negotiates (shm when native, else uds)."""
        import time

        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient

        batcher = BatchingEvaluator(device(rt), max_wait_ms=1.0)
        server = BatcherIpcServer(str(tmp_path / "batcher.sock"), batcher)
        server.start()
        client = RemoteBatcherClient(
            server.socket_path, rt, worker_label="prov-test", status_poll_s=0.05
        )
        try:
            deadline = time.monotonic() + 10.0
            while not client._connected.is_set() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert client._connected.is_set()
            inputs = [inp(i) for i in range(6)]
            outs = client.check(inputs)
            ora = oracle(rt, inputs)
            for d, o in zip(outs, ora):
                for a in o.actions:
                    assert d.actions[a].matched_rule == o.actions[a].matched_rule
                    assert d.actions[a].rule_row_id == o.actions[a].rule_row_id
                    assert d.actions[a].source == "device"
            # hot-rule counters live in the batcher process: the control
            # plane snapshot op must reach them
            snap = client.fetch_hotrules(k=5)
            assert snap["decisions"] >= 6
            assert snap["top"], snap
        finally:
            client.close()
            server.close()
            batcher.close()


# -- sharded lanes -----------------------------------------------------------


class TestShardedAttribution:
    def test_every_lane_attributes_identically(self, rt):
        from cerbos_tpu.engine.shards import build_shard_pool

        pool = build_shard_pool(
            device(rt), n_shards=2, routing="round_robin", max_wait_ms=0.0
        )
        try:
            inputs = [inp(i) for i in range(10)]
            outs = [pool.check([i])[0] for i in inputs]
            ora = oracle(rt, inputs)
            for d, o in zip(outs, ora):
                for a in o.actions:
                    assert d.actions[a].matched_rule == o.actions[a].matched_rule
                    assert d.actions[a].source == "device"
        finally:
            pool.close()


# -- hot-rule recorder -------------------------------------------------------


class TestHotRules:
    def test_snapshot_ranks_and_labels(self, rt):
        rec = HotRuleRecorder()
        outs = oracle(rt, [inp(i) for i in range(8)])
        rec.observe(outs)
        snap = rec.snapshot(k=5, rule_table=rt)
        assert snap["decisions"] == 8
        assert snap["attributed"] == 8
        assert snap["attribution_rate"] == 1.0
        assert snap["by_source"] == {"oracle": 8}
        top = snap["top"]
        assert top and top[0]["hits"] == 8
        assert top[0]["rule"].endswith("#read-any")
        assert 0.99 <= sum(e["share"] for e in top) <= 1.01

    def test_unattributed_counts_separately(self):
        rec = HotRuleRecorder()
        out = T.CheckOutput(
            request_id="r",
            resource_id="x",
            actions={
                "read": T.ActionEffect(
                    effect=T.EFFECT_DENY, policy=T.NO_POLICY_MATCH, source="device"
                )
            },
        )
        rec.observe([out])
        snap = rec.snapshot()
        assert snap["decisions"] == 1
        assert snap["attributed"] == 0
        assert snap["unattributed"] == 1
        assert snap["attribution_rate"] == 0.0

    def test_kill_switch_env(self, rt, monkeypatch):
        monkeypatch.setenv("CERBOS_TPU_NO_PROVENANCE", "1")
        rec = HotRuleRecorder()
        rec.observe(oracle(rt, [inp(0)]))
        assert rec.snapshot()["decisions"] == 0

    def test_observe_never_raises(self):
        rec = HotRuleRecorder()
        rec.observe([object()])  # garbage in, telemetry must shrug


# -- includeMeta + audit surfacing -------------------------------------------


class TestSurfacing:
    def test_include_meta_json_carries_rule_and_source(self, rt):
        from cerbos_tpu.server import convert

        body = {
            "requestId": "rq-m",
            "includeMeta": True,
            "principal": {"id": "u1", "roles": ["viewer"]},
            "resources": [
                {"resource": {"kind": "widget", "id": "w1", "attr": {"owner": "u1"}}, "actions": ["read"]}
            ],
        }
        inputs, request_id, include_meta = convert.json_to_check_inputs(body, None)
        assert include_meta
        outs = device(rt).check(inputs, EvalParams())
        resp = convert.outputs_to_json(body, outs, request_id, include_meta, provenance=True)
        meta = resp["results"][0]["meta"]["actions"]["read"]
        assert meta["matchedPolicy"] == "resource.widget.vdefault"
        assert meta["matchedRule"].endswith("#read-any")
        assert meta["source"] == "device"
        # oracle path: same rule, honestly labeled
        resp2 = convert.outputs_to_json(
            body, oracle(rt, inputs), request_id, include_meta, provenance=True
        )
        meta2 = resp2["results"][0]["meta"]["actions"]["read"]
        assert meta2["matchedRule"] == meta["matchedRule"]
        assert meta2["source"] == "oracle"
        # without the opt-in the meta block stays upstream-schema clean —
        # strict proto clients must keep parsing the default response
        plain = convert.outputs_to_json(body, outs, request_id, include_meta)
        assert set(plain["results"][0]["meta"]["actions"]["read"]) == {
            "matchedPolicy",
            "matchedScope",
        }

    def test_audit_entry_records_matched_rule(self, rt):
        from cerbos_tpu.audit.log import _entry_from_decision

        inputs = [inp(0)]
        outs = device(rt).check(inputs, EvalParams())
        entry = _entry_from_decision("c1", inputs, outs, trace_id="t1", shard=2)
        # provenance lives in the top-level PDP-extension block next to
        # traceId/shard — the Cerbos-schema checkResources part stays clean
        action = entry["provenance"][0]["actions"]["read"]
        assert action["matchedRule"].endswith("#read-any")
        assert action["source"] == "device"
        assert "matchedRule" not in entry["checkResources"]["outputs"][0]["actions"]["read"]
        assert entry["traceId"] == "t1" and entry["shard"] == 2


# -- sentinel annotation + replay --explain ----------------------------------


class TestSentinelAnnotation:
    def test_divergence_record_names_both_winning_rules(self, rt, tmp_path):
        """The acceptance drill: a seeded ``flip_effect`` produces a corpus
        record naming the winning rule on BOTH paths, and
        ``replay-divergences --explain`` renders the diff."""
        faulty = FaultInjector(device(rt), "flip_effect:1.0,seed:3")
        batcher = BatchingEvaluator(faulty, max_wait_ms=1.0)
        sentinel = ParitySentinel(
            sample_rate=1.0, storm_threshold=99, corpus_dir=str(tmp_path / "corpus")
        ).attach(batcher)
        try:
            import time

            batcher.check([inp(i) for i in range(4)])
            # the sample is enqueued by the collect thread after check()
            # settles: poll, don't just drain
            deadline = time.monotonic() + 10.0
            while sentinel.stats["divergences"] < 1 and time.monotonic() < deadline:
                sentinel.drain(timeout=0.2)
                time.sleep(0.01)
            assert sentinel.stats["divergences"] >= 1
        finally:
            sentinel.close()
            batcher.close()
        records = DivergenceCorpus.load(str(tmp_path / "corpus"))
        assert records
        _, rec = records[0]
        dev_p, ora_p = rec["device_provenance"], rec["oracle_provenance"]
        assert dev_p and ora_p
        # flip_effect corrupts the effect but PRESERVES the device's claimed
        # rule — triage sees what the device said won
        for row in dev_p:
            for ae in row["actions"].values():
                assert ae["source"] == "device"
                assert ae["matchedRule"]
        for drow, orow in zip(dev_p, ora_p):
            for a in drow["actions"]:
                assert drow["actions"][a]["matchedRule"] == orow["actions"][a]["matchedRule"]

        # the CLI renders the per-record winning-rule diff offline
        import io
        from contextlib import redirect_stdout

        from cerbos_tpu.ctl import _explain_record

        buf = io.StringIO()
        with redirect_stdout(buf):
            _explain_record(rec)
        text = buf.getvalue()
        assert "device[device]" in text
        assert "#read-any" in text

    def test_explain_record_handles_legacy_records(self, capsys):
        from cerbos_tpu.ctl import _explain_record

        _explain_record({"divergent_indices": [0]})
        assert "predates provenance" in capsys.readouterr().out

    def test_provenance_rows_shape(self, rt):
        rows = provenance_rows(oracle(rt, [inp(0)]))
        assert rows[0]["actions"]["read"]["source"] == "oracle"
        assert rows[0]["actions"]["read"]["matchedRule"].endswith("#read-any")


# -- ctl analyze --hot merge -------------------------------------------------


class TestAnalyzeHotMerge:
    def test_ranks_oracle_extinction_targets(self, tmp_path, capsys):
        from cerbos_tpu import ctl

        pol = tmp_path / "policies.yaml"
        pol.write_text(POLICIES)
        rec = HotRuleRecorder()
        rt = table()
        rec.observe(oracle(rt, [inp(i) for i in range(5)]))
        snap = rec.snapshot(k=10, rule_table=rt)
        hot = tmp_path / "hot.json"
        hot.write_text(json.dumps(snap))
        code = ctl.main(["analyze", str(pol), "--hot", str(hot)])
        out = capsys.readouterr().out
        assert code == 0
        assert "hot-rule snapshot" in out
        assert "#read-any" in out


# -- debug endpoints ---------------------------------------------------------


class TestDebugEndpoints:
    def _app(self, rt, evaluator=None):
        from cerbos_tpu.engine.engine import Engine
        from cerbos_tpu.server.server import Server
        from cerbos_tpu.server.service import CerbosService

        eng = Engine(rt, tpu_evaluator=evaluator, tpu_batch_threshold=1)
        return Server(CerbosService(eng))._http_app()

    def test_hotrules_endpoint_local(self, rt):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        rec_rt = rt
        HotRuleRecorder()  # registry warm; the endpoint uses the singleton
        from cerbos_tpu.engine.hotrules import recorder

        recorder().observe(oracle(rec_rt, [inp(i) for i in range(3)]))

        async def run():
            async with TestClient(TestServer(self._app(rec_rt))) as client:
                resp = await client.get("/_cerbos/debug/hotrules?k=3")
                body = await resp.json()
                assert resp.status == 200
                assert body["source"] == "local"
                assert body["decisions"] >= 3
                assert len(body["top"]) <= 3
                bad = await client.get("/_cerbos/debug/hotrules?k=x")
                assert bad.status == 400

        asyncio.run(run())

    def test_explain_endpoint_cross_checks(self, rt):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        app = self._app(rt, evaluator=device(rt))

        async def run():
            async with TestClient(TestServer(app)) as client:
                resp = await client.post(
                    "/_cerbos/debug/explain",
                    json={
                        "requestId": "rq-x",
                        "principal": {"id": "u1", "roles": ["viewer"]},
                        "resources": [
                            {
                                "resource": {"kind": "widget", "id": "w9", "attr": {"owner": "u1"}},
                                "actions": ["read"],
                            }
                        ],
                    },
                )
                body = await resp.json()
                assert resp.status == 200, body
                assert body["device_path"] == "device"
                act = body["results"][0]["actions"]["read"]
                assert act["agree"] is True
                assert act["device"]["matched_rule"].endswith("#read-any")
                assert act["device"]["source"] == "device"
                assert act["device"]["matched_rule"] == act["oracle"]["matched_rule"]
                assert act["device"]["matched_rule"] in act["trace_activated"]
                bad = await client.post("/_cerbos/debug/explain", data=b"{nope")
                assert bad.status == 400

        asyncio.run(run())

    def test_include_meta_provenance_header_opt_in(self, rt):
        """The HTTP check path only emits matchedRule/source when the caller
        sends X-Cerbos-TPU-Provenance — the default includeMeta response
        stays parseable by strict upstream-proto clients (the golden
        compatibility suite holds it to that)."""
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        app = self._app(rt, evaluator=device(rt))
        body = {
            "requestId": "rq-h",
            "includeMeta": True,
            "principal": {"id": "u1", "roles": ["viewer"]},
            "resources": [
                {"resource": {"kind": "widget", "id": "wh", "attr": {"owner": "u1"}}, "actions": ["read"]}
            ],
        }

        async def run():
            async with TestClient(TestServer(app)) as client:
                plain = await (await client.post("/api/check/resources", json=body)).json()
                meta = plain["results"][0]["meta"]["actions"]["read"]
                assert set(meta) == {"matchedPolicy", "matchedScope"}
                opted = await (
                    await client.post(
                        "/api/check/resources",
                        json=body,
                        headers={"X-Cerbos-TPU-Provenance": "1"},
                    )
                ).json()
                meta2 = opted["results"][0]["meta"]["actions"]["read"]
                assert meta2["matchedRule"].endswith("#read-any")
                assert meta2["source"] in ("device", "oracle")

        asyncio.run(run())
