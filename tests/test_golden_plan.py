"""The reference's query-planner golden suites through the planner.

Behavioral reference: internal/engine/engine_test.go TestQueryPlan:
policies from query_planner/policies, now pinned to
2024-01-16T10:18:27.395+13:00, auxData.jwt.customInt=42, globals
{"environment": "test"}; filters compared after stabilisation (operands of
commutative operators sorted by their JSON encoding; struct entries sorted
by key — engine_test.go:500-575).
"""

import datetime
import functools
import json

import pytest

from cerbos_tpu.cel.values import Timestamp
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import EvalParams, Principal
from cerbos_tpu.engine.types import AuxData
from cerbos_tpu.plan import Planner
from cerbos_tpu.plan.types import PlanInput
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.storage import DiskStore

from golden_loader import GOLDEN_DIR, load_cases

NOW = Timestamp.from_datetime(
    datetime.datetime(2024, 1, 16, 10, 18, 27, 395000,
                      tzinfo=datetime.timezone(datetime.timedelta(hours=13)))
)

COMMUTATIVE = {"and", "or", "eq", "ne", "add", "mult"}


@functools.lru_cache(maxsize=None)
def plan_table():
    store = DiskStore(GOLDEN_DIR + "/query_planner/policies")
    return build_rule_table(compile_policy_set(store.get_all()))


def make_params(lenient: bool) -> EvalParams:
    return EvalParams(
        globals={"environment": "test"},
        now_fn=lambda: NOW,
        lenient_scope_search=lenient,
    )


def stabilise(operand_json):
    """Mirror of engine_test.go stabiliseOperand."""
    if not isinstance(operand_json, dict) or "expression" not in operand_json:
        return operand_json
    expr = operand_json["expression"]
    ops = [stabilise(o) for o in expr.get("operands", [])]
    op = expr.get("operator", "")
    if op == "struct":
        ops.sort(key=lambda o: str(o.get("expression", {}).get("operands", [{}])[0].get("value", "")))
    if op in COMMUTATIVE:
        ops.sort(key=lambda o: json.dumps(o, sort_keys=True))
    return {"expression": {"operator": op, "operands": ops}}


def norm_values(x):
    """YAML ints vs structpb doubles: normalize numbers inside value nodes."""
    if isinstance(x, dict):
        if set(x) == {"value"}:
            v = x["value"]
            return {"value": _norm_v(v)}
        return {k: norm_values(v) for k, v in x.items()}
    if isinstance(x, list):
        return [norm_values(v) for v in x]
    return x


def _norm_v(v):
    if isinstance(v, bool) or v is None or isinstance(v, str):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, list):
        return [_norm_v(x) for x in v]
    if isinstance(v, dict):
        return {k: _norm_v(x) for k, x in v.items()}
    return v


def run_suite(name, suite, lenient):
    planner = Planner(plan_table())
    params = make_params(lenient)
    p = suite["principal"]
    principal = Principal(
        id=p["id"],
        roles=list(p.get("roles", [])),
        attr=p.get("attr", {}) or {},
        policy_version=p.get("policyVersion", ""),
        scope=p.get("scope", ""),
    )
    aux = AuxData(jwt={"customInt": 42})
    failures = []
    for i, tt in enumerate(suite.get("tests", [])):
        actions = tt.get("actions") or [tt["action"]]
        res = tt["resource"]
        inp = PlanInput(
            request_id="requestId",
            actions=list(actions),
            principal=principal,
            resource_kind=res["kind"],
            resource_attr=res.get("attr", {}) or {},
            resource_policy_version=res.get("policyVersion", ""),
            resource_scope=res.get("scope", ""),
            aux_data=aux,
            include_meta=True,
        )
        label = f"{name}#{i} {res['kind']}/{','.join(actions)}"
        if tt.get("wantErr"):
            try:
                planner.plan(inp, params)
                failures.append(f"{label}: expected error, got success")
            except Exception:
                pass
            continue
        try:
            out = planner.plan(inp, params)
        except Exception as e:  # noqa: BLE001
            failures.append(f"{label}: raised {type(e).__name__}: {e}")
            continue
        want = tt["want"]
        have = {"kind": out.kind}
        if out.condition is not None:
            have["condition"] = out.condition.to_json()
        want_n = {"kind": want["kind"]}
        if "condition" in want:
            want_n["condition"] = stabilise(norm_values(want["condition"]))
        have_n = {"kind": have["kind"]}
        if "condition" in have:
            have_n["condition"] = stabilise(norm_values(have["condition"]))
        if want_n != have_n:
            failures.append(
                f"{label}:\n  want {json.dumps(want_n, sort_keys=True)}\n  have {json.dumps(have_n, sort_keys=True)}"
            )
    return failures


COMMON = load_cases("query_planner/suite/common")
STRICT = load_cases("query_planner/suite/strict_scope_search")
LENIENT = load_cases("query_planner/suite/lenient_scope_search")


def _id(ct):
    return ct[0].rsplit("/", 1)[-1]


@pytest.mark.parametrize("case_tuple", COMMON + STRICT, ids=_id)
def test_plan_strict(case_tuple):
    name, suite = case_tuple
    failures = run_suite(name, suite, lenient=False)
    assert not failures, "\n".join(failures)


@pytest.mark.parametrize("case_tuple", COMMON + LENIENT, ids=_id)
def test_plan_lenient(case_tuple):
    name, suite = case_tuple
    failures = run_suite(name, suite, lenient=True)
    assert not failures, "\n".join(failures)


STRUCT_CMP_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: struct_cmp
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: '{"basic": 5, "pro": 20}[request.resource.attr.plan] > 10'
"""


def test_struct_matcher_ordered_comparison_divergence():
    """Differential pin for the deliberate struct-matcher deviation
    (plan/partial.py): `m[x] > c` must expand each option as
    `(value > c)`, not the reference's inverted `(c > value)`
    (struct_matcher.go:258-264 mkOption). Ground truth by direct
    evaluation: plan="pro" gives 20 > 10 = true, plan="basic" gives
    5 > 10 = false — so the residual filter must select "pro". The
    reference's inversion computes 10 > 5 / 10 > 20 and would select
    "basic" (documented in tests/golden/UNSUPPORTED.md)."""
    from cerbos_tpu.policy.parser import parse_policies

    table = build_rule_table(compile_policy_set(list(parse_policies(STRUCT_CMP_POLICY))))
    planner = Planner(table)
    out = planner.plan(
        PlanInput(
            request_id="r",
            actions=["view"],
            principal=Principal(id="p", roles=["user"]),
            resource_kind="struct_cmp",
        ),
        EvalParams(),
    )
    assert out.kind == "KIND_CONDITIONAL"
    j = json.dumps(out.condition.to_json())
    assert "pro" in j and "basic" not in j, f"filter must select the option where value>const holds: {j}"
