"""Overload-graceful serving: admission control, priority lanes, brownout.

Every controller under test takes an injectable clock (or an explicit
``now``), so the token bucket, the brownout hold timers, and the pressure
window all run on fake time — no sleeps, no flakes. Metric assertions are
deltas: the instruments are process-global (get-or-create registry) and
other suites in the same run share them.
"""

import marshal
import re
import time
from concurrent.futures import Future

import pytest

from cerbos_tpu import observability as obs
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import flight
from cerbos_tpu.engine.admission import (
    AdmissionController,
    OverloadRefused,
    PriorityClass,
    _NullTicket,
    retry_after_header,
)
from cerbos_tpu.engine.batcher import (
    BatchingEvaluator,
    _BatchFailed,
    _Pending,
    _PriorityLanes,
)
from cerbos_tpu.engine.brownout import BrownoutController
from cerbos_tpu.engine.budget import Waterfall
from cerbos_tpu.engine.ipc import RemoteBatcherClient
from cerbos_tpu.engine.pressure import HIGH_WATER, PressureMonitor
from cerbos_tpu.engine.readiness import ReadinessState

pytestmark = pytest.mark.overload


def _event_count(kind: str) -> int:
    return sum(1 for e in flight.recorder().dump()["events"] if e["kind"] == kind)


# ---------------------------------------------------------------------------
# priority classes: compilation + classification
# ---------------------------------------------------------------------------


class TestPriorityClass:
    def test_from_conf_defaults(self):
        c = PriorityClass.from_conf({"name": "gold"})
        assert (c.priority, c.weight, c.rate, c.max_concurrent, c.queue_budget) == (
            0,
            1,
            0.0,
            0,
            0,
        )
        # burst defaults to max(rate, 1): a rate below 1 rps must still
        # admit whole requests
        assert PriorityClass.from_conf({"name": "a", "rate": 0.5}).burst == 1.0
        assert PriorityClass.from_conf({"name": "a", "rate": 40}).burst == 40.0
        assert PriorityClass.from_conf({"name": "a", "rate": 40, "burst": 80}).burst == 80.0
        # priority-0 classes are protected from shed_low_priority by default
        assert PriorityClass.from_conf({"name": "a"}).sheddable is False
        assert PriorityClass.from_conf({"name": "a", "priority": 2}).sheddable is True
        assert (
            PriorityClass.from_conf({"name": "a", "priority": 2, "sheddable": False}).sheddable
            is False
        )
        # weight floors at 1 (a zero-weight lane would never drain)
        assert PriorityClass.from_conf({"name": "a", "weight": 0}).weight == 1

    def test_match_dimensions_and_globs(self):
        c = PriorityClass.from_conf(
            {
                "name": "gold",
                "match": {"roles": ["admin*"], "kinds": ["album"]},
            }
        )
        assert c.matches("u1", ["admin"], ["album"], "check")
        assert c.matches("u1", ["administrator"], ["album"], "check")
        # every NON-empty dimension must hit
        assert not c.matches("u1", ["user"], ["album"], "check")
        assert not c.matches("u1", ["admin"], ["report"], "check")
        # an empty dimension is a wildcard
        wide = PriorityClass.from_conf({"name": "any"})
        assert wide.matches("whoever", [], [], "plan")

    def test_classify_first_match_wins(self):
        ctrl = AdmissionController(clock=lambda: 0.0)
        ctrl.configure(
            {
                "enabled": True,
                "classes": [
                    {"name": "first", "match": {"principals": ["svc-*"]}},
                    {"name": "second", "match": {"principals": ["svc-a"]}},
                ],
            }
        )
        # svc-a hits both declared classes: declaration order wins
        assert ctrl.classify("svc-a").name == "first"
        assert ctrl.classify("svc-zzz").name == "first"
        # nothing matches -> the implicit default class
        assert ctrl.classify("alice").name == "default"
        assert ctrl.classify("alice").priority == 1

    def test_lane_conf_shape(self):
        c = PriorityClass.from_conf(
            {"name": "gold", "priority": 0, "weight": 4, "queueBudget": 32}
        )
        assert c.lane_conf() == ("gold", 0, 4, 32)


# ---------------------------------------------------------------------------
# admission controller: token bucket, concurrency, shed, disabled path
# ---------------------------------------------------------------------------


class TestAdmissionController:
    def _ctrl(self, conf, t0=0.0):
        state = {"now": t0}
        ctrl = AdmissionController(clock=lambda: state["now"])
        ctrl.configure(conf)
        return ctrl, state

    def test_disabled_path_hands_out_null_tickets(self):
        # no classes and no default caps: admission compiles to disabled and
        # the hot path costs one attribute read
        ctrl, _ = self._ctrl({"enabled": True, "classes": [], "default": {}})
        assert ctrl.enabled is False
        t = ctrl.try_admit(ctrl.default)
        assert isinstance(t, _NullTicket)
        t.release()  # born released; must be a no-op
        # explicit off wins even with classes declared
        ctrl2, _ = self._ctrl(
            {"enabled": False, "classes": [{"name": "gold", "rate": 1}]}
        )
        assert ctrl2.enabled is False

    def test_token_bucket_refuses_and_refills_on_fake_time(self):
        ctrl, state = self._ctrl(
            {"enabled": True, "classes": [{"name": "gold", "rate": 2, "burst": 2}]}
        )
        gold = ctrl.classes[0]
        admitted = ctrl.m_total.get(("gold", "admitted"))
        refused = ctrl.m_total.get(("gold", "refused_rate"))
        t1 = ctrl.try_admit(gold, now=0.0)
        t2 = ctrl.try_admit(gold, now=0.0)
        with pytest.raises(OverloadRefused) as ei:
            ctrl.try_admit(gold, now=0.0)
        assert ei.value.reason == "rate"
        assert ei.value.pclass == "gold"
        # the bucket is empty: a full token is 1/rate = 0.5 s away
        assert ei.value.retry_after == pytest.approx(0.5)
        # half a second of fake time refills exactly one token
        state["now"] = 0.5
        t3 = ctrl.try_admit(gold)
        with pytest.raises(OverloadRefused):
            ctrl.try_admit(gold, now=0.5)
        assert ctrl.m_total.get(("gold", "admitted")) == admitted + 3
        assert ctrl.m_total.get(("gold", "refused_rate")) == refused + 2
        for t in (t1, t2, t3):
            t.release()

    def test_concurrency_cap_and_ticket_release(self):
        ctrl, _ = self._ctrl(
            {"enabled": True, "classes": [{"name": "gold", "maxConcurrent": 1}]}
        )
        gold = ctrl.classes[0]
        t1 = ctrl.try_admit(gold, now=0.0)
        assert ctrl.m_inflight.get("gold") == 1.0
        with pytest.raises(OverloadRefused) as ei:
            ctrl.try_admit(gold, now=0.0)
        assert ei.value.reason == "concurrency"
        t1.release()
        t1.release()  # double release must not underflow the cap
        assert ctrl.m_inflight.get("gold") == 0.0
        t2 = ctrl.try_admit(gold, now=0.0)
        t2.release()

    def test_brownout_shed_refuses_sheddable_classes_only(self):
        ctrl, _ = self._ctrl(
            {
                "enabled": True,
                "classes": [
                    {"name": "gold", "priority": 0},
                    {"name": "bulk", "priority": 2},
                ],
            }
        )
        gold, bulk = ctrl.classes
        ctrl.set_shed(True)
        with pytest.raises(OverloadRefused) as ei:
            ctrl.try_admit(bulk, now=0.0)
        assert ei.value.reason == "brownout"
        # priority-0 traffic rides through the shed
        ctrl.try_admit(gold, now=0.0).release()
        ctrl.set_shed(False)
        ctrl.try_admit(bulk, now=0.0).release()

    def test_retry_after_header_is_integral_and_floored(self):
        mk = lambda ra: OverloadRefused("c", "rate", retry_after=ra)
        assert retry_after_header(mk(0.5)) == "1"
        assert retry_after_header(mk(3.2)) == "4"
        assert retry_after_header(mk(0.0)) == "1"
        assert retry_after_header(mk(0.0005)) == "1"
        # negative retry_after is clamped at construction
        assert mk(-5.0).retry_after == 0.0

    def test_snapshot_shape(self):
        ctrl, _ = self._ctrl(
            {"enabled": True, "classes": [{"name": "gold", "rate": 5, "maxConcurrent": 2}]}
        )
        ticket = ctrl.try_admit(ctrl.classes[0], now=0.0)
        snap = ctrl.snapshot()
        assert snap["enabled"] is True
        assert snap["shed_low_priority"] is False
        by_name = {c["name"]: c for c in snap["classes"]}
        assert set(by_name) == {"gold", "default"}
        assert by_name["gold"]["inflight"] == 1
        assert by_name["gold"]["maxConcurrent"] == 2
        assert by_name["gold"]["sheddable"] is False
        ticket.release()

    def test_lane_confs_cover_every_class_plus_default(self):
        ctrl, _ = self._ctrl(
            {
                "enabled": True,
                "classes": [
                    {"name": "gold", "priority": 0, "weight": 4, "queueBudget": 16},
                    {"name": "bulk", "priority": 2, "weight": 1, "queueBudget": 8},
                ],
            }
        )
        confs = ctrl.lane_confs()
        assert confs == [
            ("gold", 0, 4, 16),
            ("bulk", 2, 1, 8),
            ("default", 1, 1, 0),
        ]


# ---------------------------------------------------------------------------
# brownout ladder: hold timers, hysteresis, appliers
# ---------------------------------------------------------------------------

STAGES = {
    "enabled": True,
    "hysteresis": 0.05,
    "holdSeconds": 2.0,
    "stages": [
        {"name": "shed_audit", "enterAbove": 0.85},
        {"name": "shed_parity", "enterAbove": 0.90},
        {"name": "shed_plan", "enterAbove": 0.95},
        {"name": "shed_low_priority", "enterAbove": 0.98},
    ],
}


class TestBrownoutLadder:
    def _ctl(self):
        ctl = BrownoutController(clock=lambda: 0.0)
        ctl.configure(STAGES)
        return ctl

    def test_enter_requires_hold(self):
        ctl = self._ctl()
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=1.9)
        assert ctl.level() == 0
        ctl.observe(0.86, now=2.0)
        assert ctl.level() == 1
        assert ctl.active("shed_audit")
        assert ctl.stage_name() == "shed_audit"

    def test_hold_resets_when_score_dips(self):
        ctl = self._ctl()
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.50, now=1.0)  # excursion breaks the hold
        ctl.observe(0.86, now=1.5)
        ctl.observe(0.86, now=3.0)  # only 1.5 s of continuous pressure
        assert ctl.level() == 0
        ctl.observe(0.86, now=3.5)
        assert ctl.level() == 1

    def test_one_stage_per_observation(self):
        ctl = self._ctl()
        # even a 0.99 spike walks the ladder one rung at a time, each rung
        # needing a fresh hold of ITS threshold
        t, levels = 0.0, []
        while ctl.level() < 4 and t < 20.0:
            ctl.observe(0.99, now=t)
            levels.append(ctl.level())
            t += 1.0
        assert ctl.level() == 4
        assert all(b - a <= 1 for a, b in zip(levels, levels[1:]))
        assert ctl.stage_name() == "shed_low_priority"

    def test_hysteresis_band_holds_the_stage(self):
        ctl = self._ctl()
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=2.0)
        assert ctl.level() == 1
        # 0.82 is below enter (0.85) but above exit (0.80): stage holds
        for t in (3.0, 5.0, 9.0):
            ctl.observe(0.82, now=t)
        assert ctl.level() == 1
        # below the exit line, held for hold_s: stage releases
        ctl.observe(0.79, now=10.0)
        ctl.observe(0.79, now=12.0)
        assert ctl.level() == 0
        assert ctl.stage_name() == ""

    def test_oscillation_across_exit_line_never_flaps(self):
        ctl = self._ctl()
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=2.0)
        assert ctl.level() == 1
        enters = ctl.m_transitions.get(("shed_audit", "enter"))
        exits = ctl.m_transitions.get(("shed_audit", "exit"))
        # flip between just-below-exit and inside-the-band faster than the
        # hold: the below-timer resets every other sample, so no exit fires
        t = 3.0
        for i in range(12):
            ctl.observe(0.79 if i % 2 == 0 else 0.83, now=t)
            t += 1.0
        assert ctl.level() == 1
        assert ctl.m_transitions.get(("shed_audit", "enter")) == enters
        assert ctl.m_transitions.get(("shed_audit", "exit")) == exits

    def test_appliers_fire_on_enter_and_exit(self):
        ctl = self._ctl()
        calls = []
        ctl.bind_applier("shed_audit", lambda engaged: calls.append(engaged))
        ctl.bind_applier("shed_parity", lambda engaged: calls.append(("parity", engaged)))
        ctl.observe(0.92, now=0.0)
        ctl.observe(0.92, now=2.0)  # enter shed_audit
        ctl.observe(0.92, now=4.0)  # parity's own hold starts here
        ctl.observe(0.92, now=6.0)  # enter shed_parity
        assert calls == [True, ("parity", True)]
        ctl.observe(0.70, now=7.0)
        ctl.observe(0.70, now=9.0)   # exit shed_parity
        ctl.observe(0.70, now=10.0)  # audit's own release hold starts here
        ctl.observe(0.70, now=12.0)  # exit shed_audit
        assert calls == [True, ("parity", True), ("parity", False), False]

    def test_broken_applier_never_wedges_the_ladder(self):
        ctl = self._ctl()

        def boom(engaged):
            raise RuntimeError("applier down")

        ctl.bind_applier("shed_audit", boom)
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=2.0)
        assert ctl.level() == 1  # transition happened despite the applier

    def test_reset_and_reconfigure_release_engaged_stages(self):
        ctl = self._ctl()
        released = []
        ctl.bind_applier("shed_audit", lambda engaged: released.append(engaged))
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=2.0)
        assert ctl.level() == 1
        ctl.reset()
        assert ctl.level() == 0
        assert released == [True, False]
        # a config reload with a stage engaged must not leave work shed
        ctl.observe(0.86, now=10.0)
        ctl.observe(0.86, now=12.0)
        ctl.configure(STAGES)
        assert ctl.level() == 0
        assert released == [True, False, True, False]

    def test_snapshot_shape(self):
        ctl = self._ctl()
        ctl.observe(0.86, now=0.0)
        ctl.observe(0.86, now=2.0)
        snap = ctl.snapshot()
        assert snap["enabled"] is True
        assert snap["level"] == 1
        assert snap["stage"] == "shed_audit"
        assert [s["name"] for s in snap["stages"]] == [
            "shed_audit",
            "shed_parity",
            "shed_plan",
            "shed_low_priority",
        ]
        assert snap["stages"][0]["engaged"] is True
        assert snap["stages"][0]["exit"] == pytest.approx(0.80)
        assert snap["stages"][1]["engaged"] is False

    def test_disabled_ladder_ignores_observations(self):
        ctl = BrownoutController(clock=lambda: 0.0)
        ctl.configure({"enabled": False, "stages": STAGES["stages"]})
        ctl.observe(1.0, now=0.0)
        ctl.observe(1.0, now=10.0)
        assert ctl.level() == 0


# ---------------------------------------------------------------------------
# pressure monitor: high-water edges + observers
# ---------------------------------------------------------------------------


class TestPressureEdges:
    def _mon(self):
        mon = PressureMonitor(clock=lambda: 0.0)
        mon.configure(enabled=True, window_s=30.0, interval_s=0.5)
        return mon

    def test_rising_and_falling_edges_record_flight_events(self):
        mon = self._mon()
        load = {"pair": (10, 10)}
        mon.bind(queue=lambda: load["pair"])
        high0 = _event_count("pressure_high")
        rec0 = _event_count("pressure_recovered")
        snap = mon.sample(now=0.0)
        assert snap["score"] >= HIGH_WATER
        assert _event_count("pressure_high") == high0 + 1
        assert _event_count("pressure_recovered") == rec0
        # still high: the edge fires once per excursion, not per sample
        mon.sample(now=1.0)
        assert _event_count("pressure_high") == high0 + 1
        # the queue component is a rolling p90: recovery needs the hot
        # samples to age out of the window
        load["pair"] = (0, 10)
        snap = mon.sample(now=40.0)
        assert snap["score"] < HIGH_WATER
        assert _event_count("pressure_recovered") == rec0 + 1
        # and the next excursion records a fresh rising edge
        load["pair"] = (10, 10)
        mon.sample(now=80.0)
        assert _event_count("pressure_high") == high0 + 2

    def test_observers_fire_with_score_components_and_now(self):
        mon = self._mon()
        mon.bind(queue=lambda: (5, 10))
        seen = []
        fn = lambda score, components, now: seen.append((score, components, now))
        mon.add_observer(fn)
        mon.add_observer(fn)  # identity dedup: wired once
        mon.sample(now=7.0)
        assert len(seen) == 1
        score, components, now = seen[0]
        assert now == 7.0
        assert score == components["queue"] == 0.5
        mon.remove_observer(fn)
        mon.sample(now=8.0)
        assert len(seen) == 1

    def test_broken_observer_never_breaks_sampling(self):
        mon = self._mon()

        def boom(score, components, now):
            raise RuntimeError("observer down")

        mon.add_observer(boom)
        snap = mon.sample(now=0.0)
        assert "score" in snap

    def test_unbind_clears_sources_and_observers(self):
        mon = self._mon()
        mon.bind(queue=lambda: (10, 10))
        seen = []
        mon.add_observer(lambda *a: seen.append(a))
        mon.sample(now=0.0)
        assert len(seen) == 1
        mon.unbind()
        snap = mon.sample(now=1.0)
        assert snap["score"] == 0.0
        assert len(seen) == 1


# ---------------------------------------------------------------------------
# the control loop end to end: pressure -> brownout -> audit shed
# ---------------------------------------------------------------------------


class TestPressureDrivesBrownout:
    def test_audit_shed_engages_and_recovers(self):
        from cerbos_tpu.audit.log import AuditLog

        class Backend:
            def __init__(self):
                self.entries = []

            def write(self, entry):
                self.entries.append(entry)

        mon = PressureMonitor(clock=lambda: 0.0)
        mon.configure(enabled=True, window_s=5.0)
        ctl = BrownoutController(clock=lambda: 0.0)
        ctl.configure(STAGES)
        mon.add_observer(ctl.observe)
        backend = Backend()
        log = AuditLog(backend=backend)
        try:
            ctl.bind_applier("shed_audit", log.set_shed)
            load = {"pair": (9, 10)}
            mon.bind(queue=lambda: load["pair"])
            shed0 = ctl.m_shed.get("audit")
            # 0.9 sustained past the hold engages shed_audit via the observer
            mon.sample(now=0.0)
            mon.sample(now=2.5)
            assert ctl.active("shed_audit")
            # writes are dropped at the door and counted as evidence; the
            # global controller owns the counter, but it is the same
            # registry instrument this ctl holds
            log.write_access("dropped-1", "check")
            assert ctl.m_shed.get("audit") == shed0 + 1
            # pressure falls, the hot window ages out, the stage releases
            load["pair"] = (0, 10)
            mon.sample(now=10.0)
            mon.sample(now=13.0)
            assert not ctl.active("shed_audit")
            log.write_access("kept-1", "check")
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                ids = [e.get("callId") for e in backend.entries]
                if "kept-1" in ids:
                    break
                time.sleep(0.01)
            ids = [e.get("callId") for e in backend.entries]
            assert "kept-1" in ids
            assert "dropped-1" not in ids
        finally:
            log.close()

    def test_shed_low_priority_stage_drives_admission(self):
        ctl = BrownoutController(clock=lambda: 0.0)
        ctl.configure(STAGES)
        adm = AdmissionController(clock=lambda: 0.0)
        adm.configure(
            {"enabled": True, "classes": [{"name": "bulk", "priority": 2}]}
        )
        ctl.bind_applier("shed_low_priority", adm.set_shed)
        bulk = adm.classes[0]
        adm.try_admit(bulk, now=0.0).release()
        # drive the full ladder: each rung needs its own hold
        t = 0.0
        for _ in range(9):
            ctl.observe(0.99, now=t)
            t += 2.0
        assert ctl.stage_name() == "shed_low_priority"
        with pytest.raises(OverloadRefused) as ei:
            adm.try_admit(bulk, now=t)
        assert ei.value.reason == "brownout"
        ctl.reset()
        adm.try_admit(bulk, now=t).release()


# ---------------------------------------------------------------------------
# weighted priority lanes
# ---------------------------------------------------------------------------


def _p(pclass: str = "") -> _Pending:
    return _Pending([], None, Future(), pclass=pclass)


class TestPriorityLanes:
    def test_unconfigured_is_plain_fifo(self):
        lanes = _PriorityLanes()
        items = [_p(), _p("unknown-class"), _p()]
        for it in items:
            lanes.append(it)
        assert len(lanes) == 3
        assert [lanes.popleft() for _ in range(3)] == items
        assert not lanes

    def test_strict_priority_preempts_across_bands(self):
        lanes = _PriorityLanes()
        lanes.configure([("gold", 0, 1, 0), ("bulk", 2, 1, 0), ("default", 1, 1, 0)])
        b1, g1, d1, g2 = _p("bulk"), _p("gold"), _p(""), _p("gold")
        for it in (b1, g1, d1, g2):
            lanes.append(it)
        # arrival order is bulk-first, but gold drains first, then default
        assert [lanes.popleft() for _ in range(4)] == [g1, g2, d1, b1]

    def test_smooth_wrr_within_a_band(self):
        lanes = _PriorityLanes()
        lanes.configure([("a", 0, 3, 0), ("b", 0, 1, 0), ("default", 1, 1, 0)])
        for _ in range(4):
            lanes.append(_p("a"))
        for _ in range(4):
            lanes.append(_p("b"))
        order = [lanes.popleft().pclass for _ in range(8)]
        # nginx-style smooth WRR at 3:1 interleaves instead of bursting,
        # then the exhausted lane's band-mate drains the tail
        assert order == ["a", "a", "b", "a", "a", "b", "b", "b"]

    def test_peek_agrees_with_popleft(self):
        lanes = _PriorityLanes()
        lanes.configure([("a", 0, 3, 0), ("b", 0, 2, 0), ("default", 1, 1, 0)])
        for cls in ("b", "a", "b", "a", "a"):
            lanes.append(_p(cls))
        while lanes:
            head = lanes.peek()
            assert lanes.popleft() is head

    def test_queue_budget_bounds_one_lane_only(self):
        lanes = _PriorityLanes()
        lanes.configure([("bulk", 2, 1, 2), ("default", 1, 1, 0)])
        assert not lanes.over_budget("bulk")
        lanes.append(_p("bulk"))
        lanes.append(_p("bulk"))
        assert lanes.over_budget("bulk")
        # the budget is per-lane: default stays open
        assert not lanes.over_budget("")
        lanes.popleft()
        assert not lanes.over_budget("bulk")

    def test_reconfigure_migrates_queued_items(self):
        lanes = _PriorityLanes()
        items = [_p("gold"), _p(""), _p("gone-class")]
        for it in items:
            lanes.append(it)
        lanes.configure([("gold", 0, 4, 0), ("default", 1, 1, 0)])
        assert len(lanes) == 3
        assert lanes.depths() == {"gold": 1, "default": 2}
        # gold preempts; the unknown class rode into default in FIFO order
        assert [lanes.popleft() for _ in range(3)] == [items[0], items[1], items[2]]

    def test_remove_and_clear(self):
        lanes = _PriorityLanes()
        lanes.configure([("gold", 0, 1, 0), ("default", 1, 1, 0)])
        a, b = _p("gold"), _p("")
        lanes.append(a)
        lanes.append(b)
        lanes.remove(a)
        assert len(lanes) == 1
        with pytest.raises(ValueError):
            lanes.remove(a)
        lanes.clear()
        assert len(lanes) == 0 and not lanes.depths()


# ---------------------------------------------------------------------------
# batcher integration: queue budgets refuse at the door
# ---------------------------------------------------------------------------

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


def _plain_batcher(**kw):
    from cerbos_tpu.compile import compile_policy_set
    from cerbos_tpu.policy.parser import parse_policies
    from cerbos_tpu.ruletable import build_rule_table, check_input

    rt = build_rule_table(compile_policy_set(list(parse_policies(POLICY))))

    class PlainEvaluator:
        rule_table = rt
        schema_mgr = None

        def check(self, inputs, params=None):
            return [check_input(rt, i, params or EvalParams()) for i in inputs]

    return BatchingEvaluator(PlainEvaluator(), **kw)


def _inp(i: int) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(kind="album", id=f"a{i}", attr={}),
        actions=["view"],
    )


class TestBatcherQueueBudget:
    def test_over_budget_lane_refuses_without_touching_the_ring(self):
        # a huge min_batch + window parks enqueued requests in the lanes so
        # the budget check sees a stable backlog
        batcher = _plain_batcher(max_wait_ms=30000.0, min_batch_to_wait=10000)
        try:
            batcher.configure_lanes([("gold", 0, 4, 0), ("default", 1, 1, 1)])
            refusals0 = batcher.stats["lane_refusals"]
            mq0 = batcher.m_queue_budget.get("default")
            fut1 = batcher.check_async([_inp(0)])
            assert batcher.lane_depths() == {"default": 1}
            # the blocking path refuses instantly — no thread parked, the
            # pending never reaches the lane
            with pytest.raises(OverloadRefused) as ei:
                batcher.check([_inp(1)])
            assert ei.value.reason == "queue_budget"
            assert ei.value.retry_after == pytest.approx(0.1)
            # the async path settles the future with the ERR the IPC server
            # ships back to the front end
            fut2 = batcher.check_async([_inp(2)])
            with pytest.raises(_BatchFailed) as bf:
                fut2.result(timeout=5.0)
            assert bf.value.reason == "queue_budget"
            assert batcher.stats["lane_refusals"] == refusals0 + 2
            assert batcher.m_queue_budget.get("default") == mq0 + 2
            # the unbudgeted gold lane still admits
            fut3 = batcher.check_async([_inp(3)], pclass="gold")
            assert batcher.lane_depths() == {"gold": 1, "default": 1}
            for fut in (fut1, fut3):
                fut.cancel()
        finally:
            batcher.close()

    def test_wiring_from_admission_lane_confs(self):
        ctrl = AdmissionController(clock=lambda: 0.0)
        ctrl.configure(
            {
                "enabled": True,
                "classes": [{"name": "gold", "priority": 0, "weight": 4, "queueBudget": 2}],
            }
        )
        batcher = _plain_batcher(max_wait_ms=1.0)
        try:
            batcher.configure_lanes(ctrl.lane_confs())
            out = batcher.check([_inp(0)], pclass="gold")
            assert out[0].actions["view"].effect == "EFFECT_ALLOW"
        finally:
            batcher.close()


# ---------------------------------------------------------------------------
# pclass carriage over IPC
# ---------------------------------------------------------------------------


class TestCarrySpec:
    def test_pclass_rides_without_a_waterfall(self):
        assert RemoteBatcherClient._carry_spec(None, None) is None
        assert RemoteBatcherClient._carry_spec(None, "") is None
        assert RemoteBatcherClient._carry_spec(None, "gold") == (None, None, "gold")

    def test_pclass_appends_to_the_waterfall_carry(self):
        wf = Waterfall(t0=time.monotonic() - 0.25)
        spec = RemoteBatcherClient._carry_spec(wf, "gold")
        assert len(spec) == 3 and spec[2] == "gold"
        assert spec[0] == pytest.approx(0.25, abs=0.05)
        # no class: the pre-pclass 2-tuple carry, unchanged in shape
        bare = RemoteBatcherClient._carry_spec(wf, None)
        assert len(bare) == 2
        assert bare[0] == pytest.approx(spec[0], abs=0.05)

    def test_carry_survives_the_wire_codec_and_resume(self):
        wf = Waterfall(t0=time.monotonic() - 0.1)
        spec = RemoteBatcherClient._carry_spec(wf, "gold")
        wired = marshal.loads(marshal.dumps(spec))
        assert tuple(wired) == tuple(spec)
        # the batcher resumes the budget record by index reads, so extra
        # carry elements (the pclass) never break an older consumer
        resumed = Waterfall.from_carry(wired)
        assert resumed.age() == pytest.approx(0.1, abs=0.05)
        # class-only carry resumes no budget record and must not crash
        assert RemoteBatcherClient._carry_spec(None, "gold")[0] is None


# ---------------------------------------------------------------------------
# readiness surfaces the engaged stage
# ---------------------------------------------------------------------------


class TestReadinessBrownout:
    def test_snapshot_carries_stage_and_reason(self):
        rs = ReadinessState()
        stage = {"name": ""}
        rs.bind_brownout(lambda: stage["name"])
        snap = rs.snapshot()
        assert "brownout_stage" not in snap and snap.get("reason") is None
        stage["name"] = "shed_audit"
        snap = rs.snapshot()
        assert snap["brownout_stage"] == "shed_audit"
        assert snap["reason"] == "brownout"
        # brownout degrades the snapshot, never the serving gate
        assert snap["status"] == "ready"
        assert rs.serving()

    def test_provider_errors_read_as_no_stage(self):
        rs = ReadinessState()
        rs.bind_brownout(lambda: 1 / 0)
        assert "brownout_stage" not in rs.snapshot()


# ---------------------------------------------------------------------------
# metrics hygiene: families, help text, pooled-scrape plumbing
# ---------------------------------------------------------------------------

OVERLOAD_FAMILIES = {
    "cerbos_tpu_admission_total": (obs.CounterVec, ("pclass", "outcome")),
    "cerbos_tpu_admission_inflight": (obs.GaugeVec, "pclass"),
    "cerbos_tpu_admission_refusal_seconds": (obs.Histogram, None),
    "cerbos_tpu_admission_queue_budget_total": (obs.CounterVec, "pclass"),
    "cerbos_tpu_brownout_stage": (obs.Gauge, None),
    "cerbos_tpu_brownout_transitions_total": (obs.CounterVec, ("stage", "direction")),
    "cerbos_tpu_brownout_shed_total": (obs.CounterVec, "target"),
}


class TestMetricsHygiene:
    def test_overload_families_registered_with_help_and_labels(self):
        # the module-global controllers register the admission/brownout
        # families at import; the queue-budget counter registers with the
        # first batcher (constructed by the suite above either way)
        _plain_batcher(max_wait_ms=1.0).close()
        inst = obs.metrics().instruments()
        for name, (klass, label) in OVERLOAD_FAMILIES.items():
            assert name in inst, name
            m = inst[name]
            assert isinstance(m, klass), name
            assert re.fullmatch(r"cerbos_tpu_[a-z0-9_]+", name)
            assert m.help and len(m.help) > 10, name
            if label is not None:
                assert m.label == label, name

    def test_rendered_families_relabel_and_merge_for_pooled_scrapes(self):
        ctrl = AdmissionController(clock=lambda: 0.0)
        ctrl.configure({"enabled": True, "classes": [{"name": "gold"}]})
        ctrl.try_admit(ctrl.classes[0], now=0.0).release()
        text = obs.metrics().render()
        for name in OVERLOAD_FAMILIES:
            assert f"# TYPE {name} " in text, name
        # worker pools stamp each process's scrape with its identity before
        # merging: every admission sample line gains the worker label
        w0 = obs.relabel_metrics_text(text, "worker", "w0")
        for line in w0.splitlines():
            if line.startswith("cerbos_tpu_admission_total"):
                assert 'worker="w0"' in line, line
        merged = obs.merge_metrics_texts(w0, obs.relabel_metrics_text(text, "worker", "w1"))
        # family metadata appears once; both workers' samples survive
        assert merged.count("# TYPE cerbos_tpu_admission_total counter") == 1
        admitted = [
            line
            for line in merged.splitlines()
            if line.startswith("cerbos_tpu_admission_total")
            and 'pclass="gold"' in line
            and 'outcome="admitted"' in line
        ]
        assert {('worker="w0"' in line, 'worker="w1"' in line) for line in admitted} == {
            (True, False),
            (False, True),
        }

    def test_refusal_latency_histogram_observes(self):
        ctrl = AdmissionController(clock=lambda: 0.0)
        _, total0, count0 = ctrl.m_refusal_seconds.snapshot()
        ctrl.observe_refusal(0.002)
        ctrl.observe_refusal(-1.0)  # clamped, never negative
        _, total, count = ctrl.m_refusal_seconds.snapshot()
        assert count == count0 + 2
        assert total == pytest.approx(total0 + 0.002)


# ---------------------------------------------------------------------------
# shipped defaults keep the subsystem dormant until configured
# ---------------------------------------------------------------------------


class TestShippedDefaults:
    def test_default_overload_block_compiles_to_disabled_admission(self):
        from cerbos_tpu.config import DEFAULTS

        conf = DEFAULTS["overload"]
        ctrl = AdmissionController(clock=lambda: 0.0)
        ctrl.configure(conf)
        # no classes, no default caps: the front door stays wide open
        assert ctrl.enabled is False
        # while the brownout ladder arms with the documented stages
        ctl = BrownoutController(clock=lambda: 0.0)
        ctl.configure(conf["brownout"])
        assert ctl.enabled is True
        assert [s.name for s in ctl.stages] == [
            "shed_audit",
            "shed_parity",
            "shed_plan",
            "shed_low_priority",
        ]
        assert ctl.hold_s == 2.0
        assert ctl.stages[0].exit == pytest.approx(0.80)
