"""Full-server integration tests: real gRPC + HTTP against an in-process
server with a disk store (modeled on internal/server/tests.go)."""

import json
import time
import urllib.request

import grpc
import pytest

from cerbos_tpu.bootstrap import initialize
from cerbos_tpu.config import Config
from cerbos_tpu.server.server import Server, ServerConfig
from cerbos_tpu.server.admin import AdminService

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    policy_dir = tmp_path_factory.mktemp("policies")
    (policy_dir / "album.yaml").write_text(POLICY)
    config = Config.load(
        overrides=[
            f"storage.disk.directory={policy_dir}",
            "server.httpListenAddr=127.0.0.1:0",
            "server.grpcListenAddr=127.0.0.1:0",
            "server.adminAPI.enabled=true",
            "audit.enabled=true",
            "audit.backend=local",
            # the CPU oracle path keeps server tests independent of jax
            "engine.tpu.enabled=false",
        ]
    )
    core = initialize(config, use_tpu=False)
    admin = AdminService(core, username="cerbos", password="cerbosAdmin")
    srv = Server(
        core.service,
        ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
        admin_service=admin,
    )
    srv.start()
    yield srv
    srv.stop()
    core.close()


def http_post(server, path, body, auth=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.http_port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(auth or {})},
        method="POST",
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def http_get(server, path, auth=None):
    req = urllib.request.Request(f"http://127.0.0.1:{server.http_port}{path}", headers=auth or {})
    with urllib.request.urlopen(req) as resp:
        return resp.read()


CHECK_BODY = {
    "requestId": "test-1",
    "includeMeta": True,
    "principal": {"id": "alice", "roles": ["user"], "attr": {"dept": "eng"}},
    "resources": [
        {"actions": ["view", "delete"], "resource": {"kind": "album", "id": "a1", "attr": {"owner": "alice"}}},
        {"actions": ["view"], "resource": {"kind": "album", "id": "a2", "attr": {"owner": "bob", "public": False}}},
    ],
}


class TestHTTP:
    def test_check_resources(self, server):
        resp = http_post(server, "/api/check/resources", CHECK_BODY)
        assert resp["requestId"] == "test-1"
        r1, r2 = resp["results"]
        assert r1["actions"] == {"view": "EFFECT_ALLOW", "delete": "EFFECT_DENY"}
        assert r1["meta"]["actions"]["view"]["matchedPolicy"] == "resource.album.vdefault"
        assert r2["actions"] == {"view": "EFFECT_DENY"}
        assert resp.get("cerbosCallId")

    def test_health(self, server):
        assert json.loads(http_get(server, "/_cerbos/health")) == {"status": "SERVING"}

    def test_metrics(self, server):
        text = http_get(server, "/_cerbos/metrics").decode()
        assert "cerbos_dev_engine_check_count" in text

    def test_invalid_json(self, server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.http_port}/api/check/resources",
            data=b"{not json", headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400

    def test_limits(self, server):
        body = dict(CHECK_BODY)
        body["resources"] = [CHECK_BODY["resources"][0]] * 51
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.http_port}/api/check/resources",
            data=json.dumps(body).encode(), headers={"Content-Type": "application/json"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400

    def test_plan_resources(self, server):
        resp = http_post(server, "/api/plan/resources", {
            "requestId": "plan-1",
            "actions": ["view"],
            "principal": {"id": "alice", "roles": ["user"]},
            "resource": {"kind": "album"},
            "includeMeta": True,
        })
        assert resp["filter"]["kind"] == "KIND_CONDITIONAL"
        cond = resp["filter"]["condition"]["expression"]
        assert cond["operator"] == "or"
        debug = resp["meta"]["filterDebug"]
        assert "request.resource.attr.owner" in debug

    def test_plan_always_allowed(self, server):
        resp = http_post(server, "/api/plan/resources", {
            "requestId": "plan-2",
            "actions": ["delete"],
            "principal": {"id": "root", "roles": ["admin"]},
            "resource": {"kind": "album"},
        })
        assert resp["filter"]["kind"] == "KIND_ALWAYS_ALLOWED"

    def test_plan_always_denied(self, server):
        resp = http_post(server, "/api/plan/resources", {
            "requestId": "plan-3",
            "actions": ["delete"],
            "principal": {"id": "alice", "roles": ["user"]},
            "resource": {"kind": "album"},
        })
        assert resp["filter"]["kind"] == "KIND_ALWAYS_DENIED"


class TestGRPC:
    def test_check_resources_grpc(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2
        from cerbos_tpu.server.convert import py_to_value

        channel = grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}")
        stub = channel.unary_unary(
            "/cerbos.svc.v1.CerbosService/CheckResources",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_pb2.CheckResourcesResponse.FromString,
        )
        req = request_pb2.CheckResourcesRequest(request_id="grpc-1")
        req.principal.id = "alice"
        req.principal.roles.append("user")
        entry = req.resources.add()
        entry.actions.append("view")
        entry.resource.kind = "album"
        entry.resource.id = "a1"
        entry.resource.attr["owner"].CopyFrom(py_to_value("alice"))
        resp = stub(req, timeout=10)
        assert resp.request_id == "grpc-1"
        assert resp.results[0].actions["view"] == 1  # EFFECT_ALLOW
        channel.close()

    def test_server_info_grpc(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        channel = grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}")
        stub = channel.unary_unary(
            "/cerbos.svc.v1.CerbosService/ServerInfo",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_pb2.ServerInfoResponse.FromString,
        )
        resp = stub(request_pb2.ServerInfoRequest(), timeout=10)
        assert "cerbos-tpu" in resp.version
        channel.close()


class TestAdmin:
    AUTH = {"Authorization": "Basic " + __import__("base64").b64encode(b"cerbos:cerbosAdmin").decode()}

    def test_unauthenticated(self, server):
        with pytest.raises(urllib.error.HTTPError) as e:
            http_get(server, "/admin/policies")
        assert e.value.code == 401

    def test_list_policies(self, server):
        resp = json.loads(http_get(server, "/admin/policies", auth=self.AUTH))
        assert "resource.album.vdefault" in resp["policyIds"]

    def test_reload_store(self, server):
        assert json.loads(http_get(server, "/admin/store/reload", auth=self.AUTH)) == {}

    GRPC_AUTH = [("authorization", "Basic " + __import__("base64").b64encode(b"cerbos:cerbosAdmin").decode())]

    def _admin_call(self, server, method, req, resp_cls, metadata=None):
        import grpc

        with grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}") as ch:
            fn = ch.unary_unary(
                f"/cerbos.svc.v1.CerbosAdminService/{method}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )
            return fn(req, metadata=metadata or self.GRPC_AUTH, timeout=10)

    def test_grpc_admin_unauthenticated(self, server):
        import grpc

        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        with pytest.raises(grpc.RpcError) as e:
            self._admin_call(server, "ListPolicies", request_pb2.ListPoliciesRequest(),
                             response_pb2.ListPoliciesResponse, metadata=[("authorization", "Basic bad")])
        assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED

    def test_grpc_admin_list_and_get(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        resp = self._admin_call(server, "ListPolicies", request_pb2.ListPoliciesRequest(),
                                response_pb2.ListPoliciesResponse)
        assert "resource.album.vdefault" in resp.policy_ids

        # regexps match per component (name/version/scope), so anchored
        # patterns work like the reference's per-column filters
        resp = self._admin_call(
            server, "ListPolicies",
            request_pb2.ListPoliciesRequest(name_regexp="^album$", version_regexp="^default$"),
            response_pb2.ListPoliciesResponse)
        assert "resource.album.vdefault" in resp.policy_ids
        resp = self._admin_call(
            server, "ListPolicies",
            request_pb2.ListPoliciesRequest(name_regexp="^lbum$"),
            response_pb2.ListPoliciesResponse)
        assert not resp.policy_ids

        got = self._admin_call(server, "GetPolicy",
                               request_pb2.GetPolicyRequest(id=["resource.album.vdefault"]),
                               response_pb2.GetPolicyResponse)
        assert len(got.policies) == 1
        assert got.policies[0].resource_policy.resource == "album"
        assert got.policies[0].resource_policy.rules[0].actions == ["view"]

    def test_grpc_admin_inspect_and_reload(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        resp = self._admin_call(server, "InspectPolicies", request_pb2.InspectPoliciesRequest(),
                                response_pb2.InspectPoliciesResponse)
        result = resp.results["resource.album.vdefault"]
        assert "view" in result.actions
        self._admin_call(server, "ReloadStore", request_pb2.ReloadStoreRequest(),
                         response_pb2.ReloadStoreResponse)

    def test_audit_log(self, server):
        # ensure at least one decision exists, then wait for the async writer
        http_post(server, "/api/check/resources", CHECK_BODY)
        deadline = time.time() + 5
        entries = []
        while time.time() < deadline:
            resp = json.loads(http_get(server, "/admin/auditlog/list/decision_logs", auth=self.AUTH))
            entries = resp["entries"]
            if entries:
                break
            time.sleep(0.1)
        assert entries, "no decision log entries recorded"
        assert entries[0]["kind"] == "decision"


class TestDeprecatedAPIs:
    def test_check_resource_set(self, server):
        resp = http_post(server, "/api/check", {
            "requestId": "set-1",
            "actions": ["view"],
            "principal": {"id": "alice", "roles": ["user"]},
            "resource": {
                "kind": "album",
                "instances": {"a1": {"attr": {"owner": "alice"}}, "a2": {"attr": {"owner": "bob"}}},
            },
            "includeMeta": True,
        })
        insts = resp["resourceInstances"]
        assert insts["a1"]["actions"]["view"] == "EFFECT_ALLOW"
        assert insts["a2"]["actions"]["view"] == "EFFECT_DENY"
        assert resp["meta"]["resourceInstances"]["a1"]["actions"]["view"]["matchedPolicy"] == "resource.album.vdefault"

    def test_check_resource_batch(self, server):
        resp = http_post(server, "/api/x/check_resource_batch", {
            "requestId": "batch-1",
            "principal": {"id": "alice", "roles": ["user"]},
            "resources": [
                {"actions": ["view"], "resource": {"kind": "album", "id": "a1", "attr": {"owner": "alice"}}},
            ],
        })
        assert resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW"


class TestInspect:
    AUTH = {"Authorization": "Basic " + __import__("base64").b64encode(b"cerbos:cerbosAdmin").decode()}

    def test_inspect_policies(self, server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.http_port}/admin/policies/inspect",
            data=b"{}", headers={"Content-Type": "application/json", **self.AUTH}, method="POST",
        )
        with urllib.request.urlopen(req) as resp:
            body = json.loads(resp.read())
        insp = body["results"]["resource.album.vdefault"]
        assert "view" in insp["actions"]
        attrs = {a["name"] for a in insp["attributes"]}
        assert {"owner", "public"} <= attrs


class TestRequestBatching:
    def test_batched_serving(self, tmp_path_factory):
        """Concurrent requests coalesce into device batches (numpy backend)."""
        import concurrent.futures

        policy_dir = tmp_path_factory.mktemp("batch-policies")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(overrides=[
            f"storage.disk.directory={policy_dir}",
        ])
        core = initialize(config)  # tpu enabled (numpy fallback inside evaluator when jax off)
        core.tpu_evaluator.use_jax = False  # force numpy path for the test env
        try:
            def one(i):
                from cerbos_tpu.engine import CheckInput, Principal, Resource

                out = core.engine.check([CheckInput(
                    principal=Principal(id=f"u{i}", roles=["user"]),
                    resource=Resource(kind="album", id=f"a{i}", attr={"owner": f"u{i}"}),
                    actions=["view"],
                )])[0]
                return out.actions["view"].effect

            with concurrent.futures.ThreadPoolExecutor(max_workers=16) as pool:
                results = list(pool.map(one, range(64)))
            assert all(r == "EFFECT_ALLOW" for r in results)
            assert core.batcher is not None
            assert core.batcher.stats["batches"] >= 1
            # at least some coalescing happened
            assert core.batcher.stats["batched_requests"] == 64
        finally:
            core.close()


class TestListeners:
    def test_tls(self, tmp_path_factory):
        import ssl
        import subprocess

        tmp = tmp_path_factory.mktemp("tls")
        cert, key = str(tmp / "cert.pem"), str(tmp / "key.pem")
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "1", "-subj", "/CN=localhost"],
            check=True, capture_output=True,
        )
        policy_dir = tmp_path_factory.mktemp("tls-policies")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(overrides=[
            f"storage.disk.directory={policy_dir}", "engine.tpu.enabled=false",
        ])
        core = initialize(config, use_tpu=False)
        srv = Server(core.service, ServerConfig(
            http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0",
            tls_cert=cert, tls_key=key,
        ))
        srv.start()
        try:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            req = urllib.request.Request(f"https://127.0.0.1:{srv.http_port}/_cerbos/health")
            with urllib.request.urlopen(req, context=ctx) as resp:
                assert json.loads(resp.read())["status"] == "SERVING"
        finally:
            srv.stop()
            core.close()

    def test_unix_socket_grpc(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("uds")
        sock = str(tmp / "cerbos.sock")
        policy_dir = tmp_path_factory.mktemp("uds-policies")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(overrides=[
            f"storage.disk.directory={policy_dir}", "engine.tpu.enabled=false",
        ])
        core = initialize(config, use_tpu=False)
        srv = Server(core.service, ServerConfig(
            http_listen_addr="127.0.0.1:0", grpc_listen_addr=f"unix:{sock}",
        ))
        srv.start()
        try:
            from cerbos_tpu.api.cerbos.request.v1 import request_pb2
            from cerbos_tpu.api.cerbos.response.v1 import response_pb2

            channel = grpc.insecure_channel(f"unix:{sock}")
            stub = channel.unary_unary(
                "/cerbos.svc.v1.CerbosService/ServerInfo",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=response_pb2.ServerInfoResponse.FromString,
            )
            resp = stub(request_pb2.ServerInfoRequest(), timeout=10)
            assert "cerbos-tpu" in resp.version
            channel.close()
        finally:
            srv.stop()
            core.close()


class TestDeprecatedGRPC:
    def test_check_resource_set_grpc(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2
        from cerbos_tpu.server.convert import py_to_value

        channel = grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}")
        stub = channel.unary_unary(
            "/cerbos.svc.v1.CerbosService/CheckResourceSet",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_pb2.CheckResourceSetResponse.FromString,
        )
        req = request_pb2.CheckResourceSetRequest(request_id="set-grpc", include_meta=True)
        req.actions.append("view")
        req.principal.id = "alice"
        req.principal.roles.append("user")
        req.resource.kind = "album"
        req.resource.instances["a1"].attr["owner"].CopyFrom(py_to_value("alice"))
        req.resource.instances["a2"].attr["owner"].CopyFrom(py_to_value("bob"))
        resp = stub(req, timeout=10)
        assert resp.resource_instances["a1"].actions["view"] == 1
        assert resp.resource_instances["a2"].actions["view"] == 2
        assert resp.meta.resource_instances["a1"].actions["view"].matched_policy == "resource.album.vdefault"
        channel.close()

    def test_check_resource_batch_grpc(self, server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2
        from cerbos_tpu.server.convert import py_to_value

        channel = grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}")
        stub = channel.unary_unary(
            "/cerbos.svc.v1.CerbosService/CheckResourceBatch",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_pb2.CheckResourceBatchResponse.FromString,
        )
        req = request_pb2.CheckResourceBatchRequest(request_id="batch-grpc")
        req.principal.id = "alice"
        req.principal.roles.append("user")
        e = req.resources.add()
        e.actions.append("view")
        e.resource.kind = "album"
        e.resource.id = "a1"
        e.resource.attr["owner"].CopyFrom(py_to_value("alice"))
        resp = stub(req, timeout=10)
        assert resp.results[0].resource_id == "a1"
        assert resp.results[0].actions["view"] == 1
        channel.close()


class TestTLSHotReload:
    @staticmethod
    def _self_signed(cn: str):
        import datetime

        pytest.importorskip("cryptography", reason="TLS tests need cert generation")
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import NameOID

        key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=1))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName([x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256())
        )
        return (
            cert.public_bytes(serialization.Encoding.PEM),
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            ),
        )

    def test_cert_rotation_without_restart(self, tmp_path):
        import ssl as ssl_mod

        from cerbos_tpu.compile import compile_policy_set
        from cerbos_tpu.engine import Engine
        from cerbos_tpu.policy.parser import parse_policies
        from cerbos_tpu.server.service import CerbosService
        from cerbos_tpu.server.server import Server, ServerConfig

        cert_path, key_path = tmp_path / "tls.crt", tmp_path / "tls.key"
        pem1, key1 = self._self_signed("cerbos-one")
        cert_path.write_bytes(pem1)
        key_path.write_bytes(key1)

        engine = Engine.from_policies(compile_policy_set(list(parse_policies(POLICY))))
        srv = Server(
            CerbosService(engine),
            ServerConfig(
                http_listen_addr="127.0.0.1:0",
                grpc_listen_addr="127.0.0.1:0",
                tls_cert=str(cert_path),
                tls_key=str(key_path),
                tls_watch_interval_s=0.1,
            ),
        )
        srv.start()
        try:
            def served_cn() -> str:
                pem = ssl_mod.get_server_certificate(("127.0.0.1", srv.http_port))
                from cryptography import x509

                cert = x509.load_pem_x509_certificate(pem.encode())
                return cert.subject.rfc4514_string()

            assert "cerbos-one" in served_cn()

            pem2, key2 = self._self_signed("cerbos-two")
            cert_path.write_bytes(pem2)
            key_path.write_bytes(key2)
            deadline = time.time() + 5
            while time.time() < deadline:
                if "cerbos-two" in served_cn():
                    break
                time.sleep(0.1)
            assert "cerbos-two" in served_cn(), "rotated cert never served"

            # gRPC side also serves the rotated cert
            import grpc as grpc_mod

            creds = grpc_mod.ssl_channel_credentials(root_certificates=pem2)
            with grpc_mod.secure_channel(
                f"localhost:{srv.grpc_port}", creds,
                options=(("grpc.ssl_target_name_override", "localhost"),),
            ) as ch:
                grpc_mod.channel_ready_future(ch).result(timeout=10)
        finally:
            srv.stop()


class TestCtlGrpc:
    def test_ctl_grpc_roundtrip(self, server, capsys):
        from cerbos_tpu import ctl

        addr = f"127.0.0.1:{server.grpc_port}"
        rc = ctl.main(["--server", addr, "--grpc", "get", "policies"])
        assert rc in (0, None)
        out = capsys.readouterr().out
        assert "resource.album.vdefault" in out

        rc = ctl.main(["--server", addr, "--grpc", "get", "policy", "resource.album.vdefault"])
        assert rc in (0, None)
        out = capsys.readouterr().out
        assert "resourcePolicy" in out

        rc = ctl.main(["--server", addr, "--grpc", "store", "reload"])
        assert rc in (0, None)


class TestCORS:
    def test_preflight_and_origin_header(self, server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.http_port}/api/check/resources",
            method="OPTIONS",
            headers={"Origin": "https://app.example", "Access-Control-Request-Method": "POST"},
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 204
            assert resp.headers["Access-Control-Allow-Origin"] == "*"
            assert "POST" in resp.headers["Access-Control-Allow-Methods"]
            assert "user-agent" in resp.headers["Access-Control-Allow-Headers"]

    def test_simple_request_gets_origin(self, server):
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.http_port}/_cerbos/health",
            headers={"Origin": "https://app.example"},
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.headers["Access-Control-Allow-Origin"] == "*"


class TestOpenAPI:
    def test_swagger_document(self, server):
        doc = json.loads(http_get(server, "/schema/swagger.json"))
        assert doc["swagger"] == "2.0"
        assert "/api/check/resources" in doc["paths"]
        assert "/api/plan/resources" in doc["paths"]
        assert "/admin/policies" in doc["paths"]
        assert "Principal" in doc["definitions"]

    def test_api_explorer(self, server):
        html = http_get(server, "/").decode()
        assert "/schema/swagger.json" in html
        assert "<html" in html


class TestOtlpMetrics:
    def test_export_posts_gauges(self):
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from cerbos_tpu.observability import OTLPMetricsExporter
        from cerbos_tpu.server.service import ServiceMetrics

        received = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers.get("Content-Length", "0")))
                received.append((self.path, json.loads(body)))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        httpd = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            m = ServiceMetrics()
            m.record_check(1.5, 2)
            m.record_check(3.5, 1)
            mx = OTLPMetricsExporter(f"http://127.0.0.1:{httpd.server_address[1]}", interval_s=3600)
            mx.add_source(m.snapshot)
            mx.close()  # close flushes
            assert received and received[0][0] == "/v1/metrics"
            metrics = received[0][1]["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
            by_name = {x["name"]: x["gauge"]["dataPoints"][0]["asDouble"] for x in metrics}
            assert by_name["cerbos_dev_engine_check_count"] == 2.0
            assert by_name["cerbos_dev_engine_check_batch_size_total"] == 3.0
        finally:
            httpd.shutdown()


class TestAuditProtos:
    def test_decision_log_entry_wire_shape(self):
        """Audit proto family is wire-compatible: DecisionLogEntry round-trips
        with the reference's field numbers (audit.proto)."""
        from google.protobuf import json_format

        from cerbos_tpu.api.cerbos.audit.v1 import audit_pb2

        e = audit_pb2.DecisionLogEntry(call_id="01HXYZ")
        e.peer.address = "10.0.0.1"
        e.check_resources.inputs.add(request_id="r1")
        e.audit_trail.effective_policies["resource.doc.vdefault"].attributes["source"].string_value = "doc.yaml"
        raw = e.SerializeToString()
        back = audit_pb2.DecisionLogEntry.FromString(raw)
        assert back.call_id == "01HXYZ"
        assert back.WhichOneof("method") == "check_resources"
        j = json_format.MessageToDict(back)
        assert j["auditTrail"]["effectivePolicies"]["resource.doc.vdefault"]["attributes"]["source"] == "doc.yaml"

    def test_telemetry_proto_shape(self):
        from cerbos_tpu.api.cerbos.telemetry.v1 import telemetry_pb2

        launch = telemetry_pb2.ServerLaunch(version="1.0")
        launch.features.storage.driver = "disk"
        launch.features.storage.disk.watch = True
        launch.stats.policy.count["RESOURCE"] = 9
        back = telemetry_pb2.ServerLaunch.FromString(launch.SerializeToString())
        assert back.features.storage.WhichOneof("store") == "disk"
        assert back.stats.policy.count["RESOURCE"] == 9


class TestAuthZenProtos:
    def test_authzen_wire_shapes(self):
        from google.protobuf import json_format

        from cerbos_tpu.api.authzen.authorization.v1 import evaluation_pb2

        req = evaluation_pb2.AccessEvaluationRequest()
        req.subject.type = "user"
        req.subject.id = "alice"
        req.resource.type = "doc"
        req.action.name = "view"
        back = evaluation_pb2.AccessEvaluationRequest.FromString(req.SerializeToString())
        assert back.subject.id == "alice"
        # AuthZEN wire JSON uses snake_case metadata field names (json_name)
        meta = evaluation_pb2.MetadataResponse(access_evaluation_endpoint="/access/v1/evaluation")
        j = json_format.MessageToDict(meta)
        assert j == {"access_evaluation_endpoint": "/access/v1/evaluation"}


class TestAioGrpc:
    """The grpc.aio listener variant (server.grpcAsync): same handlers on
    the HTTP event loop; abort semantics translated by the shim."""

    @pytest.fixture(scope="class")
    def aio_server(self, tmp_path_factory):
        policy_dir = tmp_path_factory.mktemp("policies-aio")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(
            overrides=[
                f"storage.disk.directory={policy_dir}",
                "audit.enabled=true",
                "audit.backend=local",
                "engine.tpu.enabled=false",
            ]
        )
        core = initialize(config, use_tpu=False)
        admin = AdminService(core, username="cerbos", password="cerbosAdmin")
        srv = Server(
            core.service,
            ServerConfig(
                http_listen_addr="127.0.0.1:0",
                grpc_listen_addr="127.0.0.1:0",
                grpc_async=True,
            ),
            admin_service=admin,
        )
        srv.start()
        yield srv
        srv.stop()
        core.close()

    def test_check_over_aio(self, aio_server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2
        from cerbos_tpu.server.convert import py_to_value

        with grpc.insecure_channel(f"127.0.0.1:{aio_server.grpc_port}") as ch:
            stub = ch.unary_unary(
                "/cerbos.svc.v1.CerbosService/CheckResources",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=response_pb2.CheckResourcesResponse.FromString,
            )
            req = request_pb2.CheckResourcesRequest(request_id="aio-1")
            req.principal.id = "alice"
            req.principal.roles.append("user")
            entry = req.resources.add()
            entry.actions.append("view")
            entry.resource.kind = "album"
            entry.resource.id = "a1"
            entry.resource.attr["owner"].CopyFrom(py_to_value("alice"))
            resp = stub(req, timeout=10)
            assert resp.results[0].actions["view"] == 1  # EFFECT_ALLOW

    def test_abort_translates(self, aio_server):
        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        with grpc.insecure_channel(f"127.0.0.1:{aio_server.grpc_port}") as ch:
            stub = ch.unary_unary(
                "/cerbos.svc.v1.CerbosAdminService/ListPolicies",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=response_pb2.ListPoliciesResponse.FromString,
            )
            with pytest.raises(grpc.RpcError) as e:
                stub(request_pb2.ListPoliciesRequest(), timeout=10)  # no auth
            assert e.value.code() == grpc.StatusCode.UNAUTHENTICATED

    def test_admin_streaming_over_aio(self, aio_server):
        import base64

        from cerbos_tpu.api.cerbos.request.v1 import request_pb2
        from cerbos_tpu.api.cerbos.response.v1 import response_pb2

        # generate at least one decision entry
        self.test_check_over_aio(aio_server)
        auth = [("authorization", "Basic " + base64.b64encode(b"cerbos:cerbosAdmin").decode())]
        with grpc.insecure_channel(f"127.0.0.1:{aio_server.grpc_port}") as ch:
            stub = ch.unary_stream(
                "/cerbos.svc.v1.CerbosAdminService/ListAuditLogEntries",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=response_pb2.ListAuditLogEntriesResponse.FromString,
            )
            req = request_pb2.ListAuditLogEntriesRequest(
                kind=request_pb2.ListAuditLogEntriesRequest.KIND_DECISION, tail=10
            )
            entries = list(stub(req, metadata=auth, timeout=10))
            assert entries, "decision entries must stream over the aio server"
