"""Golden index-builder corpus: drive the 18 reference cases under
tests/golden/index/ through DiskStore + compile_policy_set and assert the
reference's error identities.

Each case file carries a ``files:`` map (materialized into a tempdir) and
either ``wantErrList`` (loadFailures / duplicateDefs / missingImports /
missingScopeDetails / disabledDefs) or ``wantCompilationUnits``. Where our
loader intentionally diverges from the reference, the test pins the CURRENT
behavior and points at tests/golden/UNSUPPORTED.md — if the divergence ever
closes, the pin fails and both the test and the doc must be updated.
"""

import os

import pytest
import yaml

from cerbos_tpu.compile import CompileError, compile_policy_set
from cerbos_tpu.storage.disk import BuildError, DiskStore

CASES_DIR = os.path.join(os.path.dirname(__file__), "golden", "index")

SUPPORTED = {
    "corrupt_files",
    "disabled_ancestor",
    "duplicate_definitions",
    "duplicate_scoped_policies",
    "incomplete_files",
    "intermingled_test_files",
    "missing_constants_import",
    "missing_derived_roles_import",
    "missing_scopes",
    "missing_variables_import",
    "multiple_policies_per_file",
    "schemas_in_valid_dir",
    "schemas_prepended_dir",
    "valid_files",
}
DIVERGENT = {  # see tests/golden/UNSUPPORTED.md
    "duplicate_rule_and_role_names",
    "schemas_in_wrong_dir",
    "top_level_variables_in_export_constants",
    "top_level_variables_in_export_variables",
}


def load_case(name):
    with open(os.path.join(CASES_DIR, name + ".yaml"), encoding="utf-8") as f:
        return yaml.safe_load(f)


def materialize(name, tmp_path):
    case = load_case(name)
    for rel, content in (case.get("files") or {}).items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(content)
    return case


def build(tmp_path):
    """Returns (policies, load_errors)."""
    try:
        store = DiskStore(str(tmp_path))
    except BuildError as e:
        return [], list(e.errors)
    return store.get_all(), []


def compile_details(policies):
    try:
        compile_policy_set(policies)
        return []
    except CompileError as e:
        return list(e.details)


def test_corpus_is_fully_covered():
    """Every golden case file has a test; new drops can't rot silently."""
    cases = {f[:-5] for f in os.listdir(CASES_DIR) if f.endswith(".yaml")}
    assert cases == SUPPORTED | DIVERGENT


# -- wantCompilationUnits cases ---------------------------------------------


def test_valid_files(tmp_path):
    """All 11 compilation units' definitions load; empty / comment-only
    policy files (empty_resource.yaml, commented_resource.yaml,
    empty_resource.json) are silently ignored like the reference does, and
    test.txt / *_test.yaml fixtures are skipped by the walker."""
    case = materialize("valid_files", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    want = {f for u in case["wantCompilationUnits"] for f in u["definitionFqns"]}
    assert {p.fqn() for p in policies} == want
    mains = {u["mainFqn"] for u in case["wantCompilationUnits"]}
    assert mains <= {p.fqn() for p in policies}


def test_intermingled_test_files(tmp_path):
    """Only principal.yaml indexes; *_test.yaml and testdata/ are skipped."""
    case = materialize("intermingled_test_files", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    assert [p.fqn() for p in policies] == [case["wantCompilationUnits"][0]["mainFqn"]]
    assert compile_details(policies) == []


def test_schemas_in_valid_dir(tmp_path):
    materialize("schemas_in_valid_dir", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == [] and policies == []


def test_schemas_prepended_dir(tmp_path):
    materialize("schemas_prepended_dir", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    assert len(policies) == 1
    assert compile_details(policies) == []


# -- loadFailures cases ------------------------------------------------------


def test_corrupt_files(tmp_path):
    """Exactly the reference's 4 load failures — empty / comment-only files
    in the same directory no longer pollute the error list."""
    case = materialize("corrupt_files", tmp_path)
    _, errors = build(tmp_path)
    want = case["wantErrList"]["loadFailures"]
    assert len(errors) == len(want) == 4
    for w in want:
        matching = [e for e in errors if w["file"] in e and w["error"] in e]
        assert len(matching) == 1, (w, errors)


def test_incomplete_files(tmp_path):
    """Reference phrases the oneof failure as "policyType: exactly one field
    is required in oneof"; ours puts the field name last — same identity."""
    case = materialize("incomplete_files", tmp_path)
    _, errors = build(tmp_path)
    want = case["wantErrList"]["loadFailures"]
    assert len(errors) == len(want) == 2
    for w in want:
        msg = w["error"].split(": ", 1)[-1]  # drop the leading field prefix
        assert any(w["file"] in e and msg in e for e in errors), (w, errors)


def test_multiple_policies_per_file(tmp_path):
    """Reference wording: "more than one YAML document detected"; ours names
    the count — same error identity (file + multi-document condition)."""
    case = materialize("multiple_policies_per_file", tmp_path)
    _, errors = build(tmp_path)
    (w,) = case["wantErrList"]["loadFailures"]
    assert len(errors) == 1
    assert w["file"] in errors[0]
    assert "found 2" in errors[0]


# -- duplicateDefs cases -----------------------------------------------------


@pytest.mark.parametrize("name", ["duplicate_definitions", "duplicate_scoped_policies"])
def test_duplicate_defs(tmp_path, name):
    """The duplicated policy FQN is reported once, attributed to one of the
    two defining files (the reference also carries otherFile + position;
    see UNSUPPORTED.md)."""
    case = materialize(name, tmp_path)
    _, errors = build(tmp_path)
    (w,) = case["wantErrList"]["duplicateDefs"]
    assert len(errors) == 1
    assert "duplicate policy definition cerbos." + w["policy"] in errors[0]
    assert w["file"] in errors[0] or w["otherFile"] in errors[0]


# -- missingImports cases ----------------------------------------------------


@pytest.mark.parametrize(
    "name",
    ["missing_constants_import", "missing_derived_roles_import", "missing_variables_import"],
)
def test_missing_imports(tmp_path, name):
    """Import-not-found is reported with the reference's position and JSON
    path. Cascading unknown-derived-role errors also surface (the reference
    suppresses them after the root cause; see UNSUPPORTED.md)."""
    case = materialize(name, tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    details = compile_details(policies)
    (w,) = case["wantErrList"]["missingImports"]
    found = [
        d
        for d in details
        if d.error == "import not found"
        and w["importName"] in d.description
        and d.path == w["position"]["path"]
    ]
    assert len(found) == 1, details
    assert found[0].line == w["position"]["line"]
    assert found[0].column == w["position"]["column"]
    assert found[0].file.endswith(w.get("importingFile", "resource.yaml"))


# -- missingScopeDetails cases -----------------------------------------------


def test_missing_scopes(tmp_path):
    case = materialize("missing_scopes", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    details = compile_details(policies)
    want = case["wantErrList"]["missingScopeDetails"]
    missing = {d.description for d in details if d.error == "missing policy definition"}
    assert missing == {f'Missing ancestor policy "{w["missingPolicy"]}"' for w in want}
    # the failing descendant is attributed
    for w in want:
        (desc,) = w["descendants"]
        scope = desc.rsplit("/", 1)[1]
        fname = "resource_" + scope.replace(".", "_") + ".yaml"
        assert any(d.file.endswith(fname) for d in details), (fname, details)


def test_disabled_ancestor(tmp_path):
    """A disabled ancestor breaks its descendants' scope chain. We report
    the resulting missing-ancestor (matching the reference's
    missingScopeDetails); the disabledDefs classification itself is not
    surfaced — see UNSUPPORTED.md."""
    case = materialize("disabled_ancestor", tmp_path)
    policies, errors = build(tmp_path)
    assert errors == []
    details = compile_details(policies)
    (w,) = case["wantErrList"]["missingScopeDetails"]
    assert any(
        d.error == "missing policy definition" and w["missingPolicy"] in d.description
        for d in details
    ), details


# -- documented divergences (pin current behavior) ---------------------------


def test_divergence_duplicate_rule_and_role_names(tmp_path):
    """Reference rejects duplicate rule / derived-role names at load time
    (4 loadFailures). Our loader accepts them — last definition wins at
    evaluation, matching pre-validation Cerbos. Pinned divergence."""
    case = materialize("duplicate_rule_and_role_names", tmp_path)
    assert len(case["wantErrList"]["loadFailures"]) == 4  # the reference bar
    policies, errors = build(tmp_path)
    assert errors == []
    assert len(policies) == 3


def test_divergence_schemas_in_wrong_dir(tmp_path):
    """Reference: a nested _schemas dir is a loadFailure. Ours: _schemas is
    pruned from the walk wherever it appears, so the case indexes zero
    policies with no error. Pinned divergence."""
    case = materialize("schemas_in_wrong_dir", tmp_path)
    assert case["wantErrList"]["loadFailures"]
    policies, errors = build(tmp_path)
    assert errors == [] and policies == []


@pytest.mark.parametrize(
    "name",
    ["top_level_variables_in_export_constants", "top_level_variables_in_export_variables"],
)
def test_divergence_top_level_variables(tmp_path, name):
    """Reference rejects the deprecated top-level ``variables`` field on
    export constants/variables policies. Ours tolerates (ignores) it.
    Pinned divergence."""
    case = materialize(name, tmp_path)
    assert case["wantErrList"]["loadFailures"]
    policies, errors = build(tmp_path)
    assert errors == []
    assert len(policies) == 1
