"""The Unsupported taxonomy: every raise site carries a stable reason code.

Two enforcement layers:

1. A parametrized case per reason code in :data:`condcompile.REASONS`,
   driving the condition compiler with a minimal expression that hits the
   corresponding raise site and asserting the kernel's audit trail
   (pred_reasons / oracle_reason) records exactly that code.
2. A source scan asserting every ``raise Unsupported(`` in condcompile.py
   passes ``code=`` with a key of REASONS — a new raise site added without
   a registered code fails here before it ships free-text-only.
"""

from __future__ import annotations

import math
import os
import re

import pytest

from cerbos_tpu.cel.parser import parse
from cerbos_tpu.compile import (
    CompiledCondition,
    CompiledExpr,
    CompiledVariable,
    PolicyParams,
)
from cerbos_tpu.tpu import condcompile
from cerbos_tpu.tpu.columns import StringInterner
from cerbos_tpu.tpu.condcompile import FALLBACK_REASONS, REASONS, ConditionSetCompiler

EMPTY = PolicyParams()


def _cond(src: str) -> CompiledCondition:
    return CompiledCondition(kind="expr", expr=CompiledExpr(original=src, node=parse(src)))


def _params(variables: dict[str, str] | None = None, constants: dict | None = None) -> PolicyParams:
    return PolicyParams(
        constants=dict(constants or {}),
        ordered_variables=tuple(
            CompiledVariable(name=n, expr=CompiledExpr(original=s, node=parse(s)))
            for n, s in (variables or {}).items()
        ),
    )


# code -> (expression, params, expect_oracle_only). Each expression is the
# smallest condition that reaches the raise site tagged with that code.
CASES: dict[str, tuple[str, PolicyParams, bool]] = {
    # inlining failures fire before the expr-level catch can allocate a
    # predicate column (the predicate would reference the same undefined
    # name), so these four class the whole kernel oracle-only
    "inline_too_deep": ("V.loop", _params(variables={"loop": "V.loop"}), True),
    "undefined_variable": ("V.nope", EMPTY, True),
    "undefined_constant": ("C.nope", EMPTY, True),
    "undefined_global": ("G.nope", EMPTY, True),
    "non_literal_list_element": ("R.attr.x in [R.attr.y]", EMPTY, False),
    "operand_unsupported": ("size(R.attr.x) == 1", EMPTY, False),
    "unsupported_function": ('startsWith(R.attr.x, "a")', EMPTY, False),
    "non_bool_literal": ("1", EMPTY, False),
    "unsupported_bool_expr": ("[1, 2]", EMPTY, False),
    "has_on_non_path": ("has(V.obj.foo)", _params(variables={"obj": "[1]"}), False),
    "bad_timestamp_constant": (
        'timestamp(R.attr.t) < timestamp("garbage")',
        EMPTY,
        False,
    ),
    "mixed_timestamp_equality": ("timestamp(R.attr.t) == R.attr.x", EMPTY, False),
    "const_const_equality": ("1 == 2", EMPTY, False),
    "list_equality": ('R.attr.x == ["a"]', EMPTY, False),
    "unsupported_equality_constant": ('R.attr.x == b"ab"', EMPTY, False),
    "mixed_timestamp_ordering": ("timestamp(R.attr.t) < R.attr.x", EMPTY, False),
    "const_const_ordering": ("1 < 2", EMPTY, False),
    "string_ordering_constant": ('R.attr.x < "m"', EMPTY, False),
    "non_numeric_ordering_constant": ("R.attr.x < true", EMPTY, False),
    "nan_ordering_constant": (
        "R.attr.x < C.nanval",
        _params(constants={"nanval": math.nan}),
        False,
    ),
    "unsupported_membership": ("1 in R.attr.y", EMPTY, False),
    # runtime-referencing conditions can't even become predicate columns:
    # the whole kernel goes oracle-only and the code lands in oracle_reason
    "operand_unsupported@runtime": (
        '"admin" in runtime.effectiveDerivedRoles',
        EMPTY,
        True,
    ),
    # plan-mode verdicts: the kernel stays device-evaluable for check
    # traffic, but plan_reason routes it to the symbolic planner fallback
    "plan_time_dependent": ("timestamp(R.attr.t) < now()", EMPTY, False),
    "plan_unknown_resource_field": ('R.id == "x"', EMPTY, False),
}


def _kernel_codes(src: str, params: PolicyParams):
    comp = ConditionSetCompiler({}, StringInterner())
    cid = comp.cond_id(_cond(src), params)
    k = comp.kernels[cid]
    pred_codes = {c for c, _msg, _node in k.pred_reasons}
    oracle_code = k.oracle_reason[0] if k.oracle_reason is not None else None
    return k, pred_codes, oracle_code


@pytest.mark.parametrize("case", sorted(CASES), ids=sorted(CASES))
def test_reason_code_assigned(case):
    code = case.split("@", 1)[0]
    src, params, oracle_only = CASES[case]
    k, pred_codes, oracle_code = _kernel_codes(src, params)
    if code.startswith("plan_"):
        # plan verdicts don't disturb the check path: the kernel keeps its
        # device emit and the rejection lands in plan_reason only
        assert k.emit is not None, f"{src!r} should stay device-evaluable"
        assert k.plan_reason is not None and k.plan_reason[0] == code
        return
    if oracle_only:
        assert k.emit is None, f"{src!r} should be oracle-only"
        assert oracle_code == code
    else:
        assert k.emit is not None, f"{src!r} should fall back to a predicate column"
        assert code in pred_codes, f"{src!r} recorded {pred_codes}, wanted {code}"
        # the audit trail carries the offending node for source positions
        assert any(c == code and node is not None for c, _m, node in k.pred_reasons)


def test_every_reason_code_exercised():
    exercised = {c.split("@", 1)[0] for c in CASES}
    assert exercised == set(REASONS), (
        "REASONS and the case table drifted apart: "
        f"missing={set(REASONS) - exercised} extra={exercised - set(REASONS)}"
    )


def test_pred_reasons_counted_in_metrics():
    from cerbos_tpu.observability import metrics

    vec = metrics().counter_vec(
        "cerbos_tpu_cond_compile_unsupported_total",
        "Condition fragments rejected by the device compiler, by stable reason code",
    )
    before = vec.get("const_const_equality")
    _kernel_codes("1 == 2", EMPTY)
    assert vec.get("const_const_equality") == before + 1


SRC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "cerbos_tpu",
    "tpu",
    "condcompile.py",
)


def _raise_statements(text: str) -> list[str]:
    """Every ``raise Unsupported(...)`` statement, joined across lines."""
    out = []
    for m in re.finditer(r"raise Unsupported\(", text):
        depth = 0
        for i in range(m.end() - 1, len(text)):
            if text[i] == "(":
                depth += 1
            elif text[i] == ")":
                depth -= 1
                if depth == 0:
                    out.append(text[m.start() : i + 1])
                    break
    return out


def test_every_raise_site_has_registered_code():
    with open(SRC_PATH, encoding="utf-8") as f:
        text = f.read()
    sites = _raise_statements(text)
    assert sites, "no raise sites found — scan is broken"
    codes_seen = set()
    for stmt in sites:
        m = re.search(r"code=\"([a-z_]+)\"", stmt)
        assert m, f"raise site without a stable code=: {stmt}"
        assert m.group(1) in REASONS, f"code {m.group(1)!r} not registered in REASONS"
        codes_seen.add(m.group(1))
        assert "node=" in stmt, f"raise site without node= (source positions): {stmt}"
    assert codes_seen == set(REASONS), (
        f"REASONS drift: unraised={set(REASONS) - codes_seen} "
        f"unregistered={codes_seen - set(REASONS)}"
    )


def test_fallback_reasons_registered():
    # the fallback-tag audit trail uses its own registry; every reason the
    # compiler records must be documented there
    comp = ConditionSetCompiler({}, StringInterner())
    cid = comp.cond_id(_cond("R.attr.x == R.attr.y"), EMPTY)
    k = comp.kernels[cid]
    assert k.fallback_tags, "path==path equality should register fallback tags"
    for path, reasons in k.fallback_reasons.items():
        assert path in k.fallback_tags
        for r in reasons:
            assert r in FALLBACK_REASONS


def test_unsupported_carries_code_and_node_defaults():
    err = condcompile.Unsupported("boom")
    assert err.code == "unsupported"
    assert err.node is None
