"""Batched PlanResources: differential parity, routing, the plan lane,
and the plan-mode parity sentinel.

The contract under test (docs/PLAN.md): for any (principal, action,
known-attrs) query, ``BatchPlanner.plan_batch`` must produce a serialized
filter AST byte-identical to the sequential ``Planner`` — the device
ternary path only ever replaces a symbolic sub-walk whose outcome the
static analyzer proved it can reproduce (``condcompile.plan_verdict``).
"""

import json
import random
import threading
from concurrent.futures import Future

import pytest

from cerbos_tpu.engine import EvalParams, Principal
from cerbos_tpu.engine.admission import OverloadRefused
from cerbos_tpu.engine.batcher import BatchingEvaluator, _Pending
from cerbos_tpu.engine.sentinel import ParitySentinel
from cerbos_tpu.engine.types import AuxData
from cerbos_tpu.plan import BatchPlanner, Planner
from cerbos_tpu.plan.types import PlanInput

from test_golden_plan import (
    COMMON,
    LENIENT,
    STRICT,
    make_params,
    plan_table,
)
from test_latency_budget import OracleEvaluator, inp as check_inp, table as check_table

pytestmark = pytest.mark.plan_batch


def canon(out) -> str:
    """The parity currency: byte-exact serialized filter AST."""
    return json.dumps(out.to_json(), sort_keys=True)


def suite_queries(suite):
    """PlanInputs for every non-error test of one golden suite (mirrors
    test_golden_plan.run_suite construction, including plan_case_05/06)."""
    p = suite["principal"]
    principal = Principal(
        id=p["id"],
        roles=list(p.get("roles", [])),
        attr=p.get("attr", {}) or {},
        policy_version=p.get("policyVersion", ""),
        scope=p.get("scope", ""),
    )
    aux = AuxData(jwt={"customInt": 42})
    queries = []
    for tt in suite.get("tests", []):
        if tt.get("wantErr"):
            continue
        actions = tt.get("actions") or [tt["action"]]
        res = tt["resource"]
        queries.append(
            PlanInput(
                request_id="requestId",
                actions=list(actions),
                principal=principal,
                resource_kind=res["kind"],
                resource_attr=res.get("attr", {}) or {},
                resource_policy_version=res.get("policyVersion", ""),
                resource_scope=res.get("scope", ""),
                aux_data=aux,
                include_meta=True,
            )
        )
    return queries


class TestGoldenCorpusParity:
    """Differential harness over the full golden plan corpus: every suite
    (common / strict / lenient, incl. query_planner_filter case_05/06
    contexts) through BOTH planners, asserting byte-identical output."""

    @pytest.mark.parametrize("lenient", [False, True], ids=["strict", "lenient"])
    def test_full_corpus_byte_exact(self, lenient):
        rt = plan_table()
        params = make_params(lenient)
        sequential = Planner(rt)
        batched = BatchPlanner(rt, globals_={"environment": "test"})
        suites = COMMON + (LENIENT if lenient else STRICT)
        total = 0
        for name, suite in suites:
            queries = suite_queries(suite)
            if not queries:
                continue
            want = [canon(sequential.plan(q, params)) for q in queries]
            have = [canon(o) for o in batched.plan_batch(queries, params)]
            for i, (w, h) in enumerate(zip(want, have)):
                assert w == h, f"{name}#{i}: batched filter diverged\n want {w}\n have {h}"
            total += len(queries)
        assert total > 50  # the corpus is non-trivial
        # the device path must actually carry traffic — a silently
        # all-symbolic planner would pass parity while proving nothing
        assert batched.stats.device_rules > 0, batched.stats.as_dict()
        assert batched.stats.device_queries > 0, batched.stats.as_dict()

    def test_mismatched_globals_go_symbolic_but_stay_correct(self):
        rt = plan_table()
        params = make_params(False)
        sequential = Planner(rt)
        # compiled against DIFFERENT globals than params carry: the whole
        # batch must route symbolic (never trust stale constant folds)
        batched = BatchPlanner(rt, globals_={"environment": "prod"})
        name, suite = COMMON[0]
        queries = suite_queries(suite)
        have = [canon(o) for o in batched.plan_batch(queries, params)]
        want = [canon(sequential.plan(q, params)) for q in queries]
        assert have == want
        assert batched.stats.device_rules == 0, batched.stats.as_dict()


class TestRandomizedParity:
    """Property-style sweep: randomized (principal, action, known-attrs)
    queries — including attr subsets the policies never name and unknown
    roles/kinds — byte-identical through both planners."""

    ATTR_POOL = [
        "owner",
        "public",
        "dept",
        "team",
        "status",
        "hidden",
        "GlobalID",
        "geographies",
        "classification",
    ]
    VALUE_POOL = [True, False, 0, 1, 42, "x", "eng", "GB", "", ["GB", "FR"], None]

    def _random_query(self, rng, kinds, roles, actions):
        n_attr = rng.randrange(0, 4)
        attrs = {
            rng.choice(self.ATTR_POOL): rng.choice(self.VALUE_POOL)
            for _ in range(n_attr)
        }
        principal = Principal(
            id=f"u{rng.randrange(5)}",
            roles=rng.sample(roles, k=rng.randrange(1, min(3, len(roles)) + 1)),
            attr={"dept": rng.choice(["eng", "sales"]), "GlobalID": rng.randrange(3)}
            if rng.random() < 0.7
            else {},
        )
        return PlanInput(
            request_id="rand",
            actions=[rng.choice(actions)],
            principal=principal,
            resource_kind=rng.choice(kinds),
            resource_attr=attrs,
            include_meta=rng.random() < 0.5,
        )

    def test_randomized_queries_byte_exact(self):
        rt = plan_table()
        params = make_params(False)
        sequential = Planner(rt)
        batched = BatchPlanner(rt, globals_={"environment": "test"})
        kinds = sorted({n for n, s in COMMON for t in s.get("tests", []) for n in [t["resource"]["kind"]]})
        actions = ["view", "edit", "delete", "approve", "report"]
        roles = ["user", "employee", "manager", "admin", "boss"]
        rng = random.Random(20260807)
        queries = [self._random_query(rng, kinds, roles, actions) for _ in range(150)]
        outs = batched.plan_batch(queries, params)
        for i, (q, o) in enumerate(zip(queries, outs)):
            want = canon(sequential.plan(q, params))
            assert canon(o) == want, f"query {i} diverged:\n want {want}\n have {canon(o)}"


def album_plan_input(i: int, **attr) -> PlanInput:
    return PlanInput(
        request_id=f"pq{i}",
        actions=["view"],
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource_kind="album",
        resource_attr=attr,
    )


def make_plan_batcher(**kw):
    rt = check_table()
    kw.setdefault("max_wait_ms", 1.0)
    b = BatchingEvaluator(OracleEvaluator(rt), **kw)
    b.plan_planner = BatchPlanner(rt)
    return rt, b


class TestPlanLane:
    def test_plan_through_batcher_matches_sequential(self):
        rt, b = make_plan_batcher()
        try:
            sequential = Planner(rt)
            q = album_plan_input(1, public=True)
            out = b.plan([q])
            assert len(out) == 1
            assert canon(out[0]) == canon(sequential.plan(q, EvalParams()))
            assert b.stats["plan_batches"] == 1
        finally:
            b.close()

    def test_concurrent_plans_coalesce_and_stay_byte_exact(self):
        rt, b = make_plan_batcher(max_wait_ms=5.0, min_batch_to_wait=4)
        try:
            sequential = Planner(rt)
            queries = [
                album_plan_input(i, **({"public": True} if i % 3 == 0 else {}))
                for i in range(12)
            ]
            results: dict[int, str] = {}
            errors: list[BaseException] = []

            def worker(i: int) -> None:
                try:
                    results[i] = canon(b.plan([queries[i]])[0])
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
            assert not errors, errors
            for i, q in enumerate(queries):
                assert results[i] == canon(sequential.plan(q, EvalParams()))
        finally:
            b.close()

    def test_configure_lanes_appends_plan_lane_below_all_bands(self):
        rt, b = make_plan_batcher()
        try:
            b.configure_lanes([("gold", 0, 4, 0), ("default", 1, 1, 0)])
            lanes = b._queue._lanes
            assert "plan" in lanes
            assert lanes["plan"].priority > max(lanes["gold"].priority, lanes["default"].priority)
            assert lanes["plan"].budget == b.PLAN_QUEUE_BUDGET
            # an explicitly configured plan lane is honored, not duplicated
            b.configure_lanes([("gold", 0, 4, 0), ("plan", 9, 2, 7)])
            assert b._queue._lanes["plan"].budget == 7
        finally:
            b.close()

    def test_plan_queue_budget_refuses_with_overload(self):
        rt, b = make_plan_batcher()
        try:
            b.configure_lanes([("gold", 0, 1, 0), ("plan", 1, 1, 1)])
            # park a pending in the plan lane without waking the drain loop:
            # the next plan() must refuse at the lane budget, not queue behind
            with b._lock:
                b._queue.append(
                    _Pending([album_plan_input(0)], None, Future(), pclass="plan", kind="plan")
                )
            with pytest.raises(OverloadRefused) as ei:
                b.plan([album_plan_input(1)])
            assert ei.value.pclass == "plan"
            assert ei.value.reason == "queue_budget"
        finally:
            b.close()

    def test_plan_failure_falls_back_sequentially_per_query(self):
        rt, b = make_plan_batcher()
        try:
            boom = {"n": 0}
            orig = b.plan_planner.plan_batch

            def exploding(inputs, params=None):
                boom["n"] += 1
                raise RuntimeError("vectorized path down")

            b.plan_planner.plan_batch = exploding
            sequential = Planner(rt)
            q = album_plan_input(2, public=True)
            out = b.plan([q])
            assert canon(out[0]) == canon(sequential.plan(q, EvalParams()))
            assert boom["n"] == 1
            assert b.stats["plan_fallbacks"] == 1
            b.plan_planner.plan_batch = orig
        finally:
            b.close()


@pytest.mark.chaos
class TestPlanBrownoutChaos:
    def test_plan_refusals_lose_zero_check_requests(self):
        """The chaos leg: with the plan lane wedged at budget, a burst of
        interleaved plan+check traffic must refuse ONLY plan queries —
        every check-lane request still gets a decision."""
        rt, b = make_plan_batcher(max_wait_ms=1.0)
        try:
            b.configure_lanes([("default", 0, 1, 0), ("plan", 1, 1, 1)])
            with b._lock:
                b._queue.append(
                    _Pending([album_plan_input(0)], None, Future(), pclass="plan", kind="plan")
                )
            # drain loop is still asleep (the park bypassed the wakeup), so
            # the lane budget is deterministically exhausted right now
            with pytest.raises(OverloadRefused) as ei:
                b.plan([album_plan_input(99)])
            assert ei.value.pclass == "plan"
            assert ei.value.reason == "queue_budget"

            check_ok = []
            plan_ok = []
            plan_refused = []
            errors = []

            def do_check(i: int) -> None:
                try:
                    out = b.check([check_inp(i)])
                    assert len(out) == 1
                    check_ok.append(i)
                except BaseException as e:  # noqa: BLE001
                    errors.append(("check", i, e))

            def do_plan(i: int) -> None:
                # once checks wake the drain loop the parked flight clears,
                # so burst plans may be served OR refused — both are fine;
                # what is NEVER fine is a lost check decision
                try:
                    b.plan([album_plan_input(i)])
                    plan_ok.append(i)
                except OverloadRefused:
                    plan_refused.append(i)
                except BaseException as e:  # noqa: BLE001
                    errors.append(("plan", i, e))

            threads = []
            for i in range(30):
                threads.append(threading.Thread(target=do_check, args=(i,)))
                if i % 3 == 0:
                    threads.append(threading.Thread(target=do_plan, args=(i,)))
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=15)
            assert not errors, errors
            assert len(check_ok) == 30  # zero check-lane losses
            assert len(plan_ok) + len(plan_refused) == 10  # every plan settled
        finally:
            b.close()


@pytest.mark.parity_sentinel
class TestPlanParitySentinel:
    def test_plan_batches_replay_clean(self):
        rt, b = make_plan_batcher()
        sent = ParitySentinel(sample_rate=1.0).attach(b)
        try:
            b.plan([album_plan_input(1, public=True)])
            b.plan([album_plan_input(2)])
            assert sent.drain(timeout=10)
            snap = sent.snapshot()
            assert snap["plan_checks"] >= 2
            assert snap["plan_divergences"] == 0
        finally:
            sent.close()
            b.close()

    def test_corrupted_plan_output_is_a_divergence(self, tmp_path):
        rt = check_table()
        planner = BatchPlanner(rt)
        sent = ParitySentinel(sample_rate=1.0, corpus_dir=str(tmp_path))
        try:
            q = album_plan_input(3)
            good = planner.plan_batch([q])
            bad = planner.plan_batch([album_plan_input(3, public=True)])

            class FakeBatcher:
                shard_id = 0
                plan_planner = planner
                _batch_seq = 7

            # feed the sentinel a batch whose recorded output does NOT
            # match what the sequential planner produces for q
            sent.observe_plan_batch(FakeBatcher(), [q], None, bad)
            assert sent.drain(timeout=10)
            snap = sent.snapshot()
            assert snap["plan_checks"] == 1
            assert snap["plan_divergences"] == 1
            from cerbos_tpu.engine.sentinel import DivergenceCorpus

            records = list(DivergenceCorpus.load(str(tmp_path)))
            assert records, "divergence must be captured in the corpus"
            # and a clean batch replays clean
            sent.observe_plan_batch(FakeBatcher(), [q], None, good)
            assert sent.drain(timeout=10)
            assert sent.snapshot()["plan_divergences"] == 1
        finally:
            sent.close()

    def test_shed_pauses_plan_sampling(self):
        rt, b = make_plan_batcher()
        sent = ParitySentinel(sample_rate=1.0).attach(b)
        try:
            sent.set_shed(True)
            b.plan([album_plan_input(4)])
            assert sent.drain(timeout=5)
            assert sent.snapshot()["plan_checks"] == 0
            sent.set_shed(False)
            b.plan([album_plan_input(5)])
            assert sent.drain(timeout=5)
            assert sent.snapshot()["plan_checks"] == 1
        finally:
            sent.close()
            b.close()
