"""Remote JWKS: fetch, cache, rotation refresh-on-miss, keep-cached-on-failure.

Local in-process HTTP server; real RSA keys and signatures (jwt.go:40-242).
"""

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

pytest.importorskip("cryptography", reason="JWKS rotation tests sign real RSA tokens")
from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import padding, rsa

from cerbos_tpu.auxdata import AuxDataManager, JWTError, load_keyset


def _b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _jwk(key, kid="test-key"):
    pub = key.public_key().public_numbers()
    # kid and alg are REQUIRED by key-set validation (jwt.go; auxdata corpus)
    return {
        "kty": "RSA",
        "kid": kid,
        "alg": "RS256",
        "n": _b64(pub.n.to_bytes((pub.n.bit_length() + 7) // 8, "big")),
        "e": _b64(pub.e.to_bytes((pub.e.bit_length() + 7) // 8, "big")),
    }


def _sign(key, claims: dict) -> str:
    header = _b64(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    payload = _b64(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = key.sign(signing_input, padding.PKCS1v15(), hashes.SHA256())
    return f"{header}.{payload}.{_b64(sig)}"


class _JWKSServer:
    def __init__(self):
        self.keys = []
        self.fail = False
        self.hits = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                outer.hits += 1
                if outer.fail:
                    self.send_error(503)
                    return
                body = json.dumps({"keys": [_jwk(k) for k in outer.keys]}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


@pytest.fixture()
def jwks_server():
    srv = _JWKSServer()
    yield srv
    srv.stop()


def _manager(srv, refresh=3600.0, min_refresh=0.0):
    ks = load_keyset({"id": "remote", "remote": {
        "url": f"http://127.0.0.1:{srv.port}/jwks.json",
        "refreshInterval": refresh,
        "minRefreshInterval": min_refresh,
    }})
    return AuxDataManager([ks])


def test_verify_against_served_jwks(jwks_server):
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks_server.keys = [key]
    mgr = _manager(jwks_server)
    aux = mgr.extract(_sign(key, {"sub": "alice", "scope": "admin"}))
    assert aux.jwt["sub"] == "alice"
    # second verify uses the cache, not another fetch
    mgr.extract(_sign(key, {"sub": "bob"}))
    assert jwks_server.hits == 1


def test_rotation_refreshes_on_miss(jwks_server):
    old = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    new = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks_server.keys = [old]
    mgr = _manager(jwks_server)
    mgr.extract(_sign(old, {"sub": "a"}))
    # signer rotates; the endpoint now serves only the new key
    jwks_server.keys = [new]
    aux = mgr.extract(_sign(new, {"sub": "rotated"}))  # forces one refresh
    assert aux.jwt["sub"] == "rotated"
    assert jwks_server.hits == 2
    # the old key is gone from the set: old tokens now fail
    with pytest.raises(JWTError):
        mgr.extract(_sign(old, {"sub": "stale"}))


def test_fetch_failure_keeps_cached_keys(jwks_server):
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks_server.keys = [key]
    mgr = _manager(jwks_server, refresh=0.0)  # stale on every call
    mgr.extract(_sign(key, {"sub": "a"}))
    jwks_server.fail = True
    # endpoint down: cached keys keep verifying
    aux = mgr.extract(_sign(key, {"sub": "b"}))
    assert aux.jwt["sub"] == "b"


def test_no_cache_and_down_endpoint_errors(jwks_server):
    jwks_server.fail = True
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    mgr = _manager(jwks_server)
    with pytest.raises(JWTError):
        mgr.extract(_sign(key, {"sub": "a"}))


def test_forced_refresh_is_throttled(jwks_server):
    """A flood of bad-signature tokens must not hammer the JWKS endpoint:
    refresh-on-miss is rate-limited by minRefreshInterval."""
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    jwks_server.keys = [key]
    mgr = _manager(jwks_server, min_refresh=300.0)
    mgr.extract(_sign(key, {"sub": "a"}))
    for _ in range(20):
        with pytest.raises(JWTError):
            mgr.extract(_sign(other, {"sub": "forged"}))
    # initial fetch only; the 20 misses were throttled
    assert jwks_server.hits == 1
