"""The memo-cold workload must preserve the replay workload's decisions.

requests_unique's whole claim (bench.py memo_cold, loadtest --cold) is
"unique values, same decision mix": every condition's truth value survives
the uniquification. This pins it by checking per-request effects against
the unjittered requests() the variant derives from.
"""

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import EvalParams
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.util import bench_corpus


def test_requests_unique_preserves_decisions():
    n_mods = 10
    rt = build_rule_table(
        compile_policy_set(list(parse_policies(bench_corpus.corpus_yaml(n_mods))))
    )
    params = EvalParams()
    base = bench_corpus.requests(384, n_mods, seed=5)
    uniq = bench_corpus.requests_unique(384, n_mods, seed=5)
    assert len(base) == len(uniq)
    mismatches = []
    for i, (b, u) in enumerate(zip(base, uniq)):
        assert b.actions == u.actions
        wb = check_input(rt, b, params)
        wu = check_input(rt, u, params)
        eb = {a: e.effect for a, e in wb.actions.items()}
        eu = {a: e.effect for a, e in wu.actions.items()}
        if eb != eu:
            mismatches.append((i, b.resource.kind, eb, eu))
    assert not mismatches, f"{len(mismatches)} decision flips, first: {mismatches[0]}"


def test_requests_unique_values_are_unique():
    uniq = bench_corpus.requests_unique(128, 10, seed=9)
    assert len({u.principal.id for u in uniq} | {u.resource.id for u in uniq}) == 2 * len(uniq)
    # numeric attrs differ across requests that share a base value
    scores = [u.resource.attr["score"] for u in uniq if "score" in u.resource.attr]
    assert len(set(scores)) == len(scores)
