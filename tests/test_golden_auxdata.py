"""Reference auxdata corpus: JWT key-set loading and validation.

Mirrors internal/auxdata/jwt_test.go TestKeySet: each case carries key
material (bare JWK, JWKS, or PEM) loaded three ways — inline base64 data,
file path, and a remote URL served over HTTP — asserting either successful
key-set construction or the reference's validation error text (missing /
empty kid, missing / invalid alg; remote lookups wrap parse failures).
"""

import base64
import http.server
import os
import threading

import pytest
import yaml

from cerbos_tpu.auxdata import JWTError, RemoteJWKS, load_keyset, parse_key_material

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "auxdata")

CASES = sorted(f for f in os.listdir(CORPUS) if f.endswith(".yaml"))


@pytest.fixture(scope="module")
def key_server(tmp_path_factory):
    root = tmp_path_factory.mktemp("keys")
    handler = lambda *a, **kw: http.server.SimpleHTTPRequestHandler(*a, directory=str(root), **kw)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield root, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


@pytest.mark.parametrize("case", CASES)
def test_keyset_case(case, key_server, tmp_path):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = yaml.safe_load(f)
    key = tc["input"]["key"]
    pem = bool(tc["input"].get("pem"))
    want_err = tc.get("wantErr", "")
    want_local_err = tc.get("wantLocalErr", "")
    want_remote_err = tc.get("wantRemoteErr", "")

    # local: inline data
    conf_data = {"id": "t", "local": {"data": base64.b64encode(key.encode()).decode(), "pem": pem}}
    # local: file
    path = tmp_path / "key"
    path.write_text(key)
    conf_file = {"id": "t", "local": {"file": str(path), "pem": pem}}

    for conf in (conf_data, conf_file):
        if want_err or want_local_err:
            with pytest.raises(JWTError) as exc:
                _load_local(key, pem)
            assert (want_err or want_local_err) in str(exc.value), case
        else:
            keys = _load_local(key, pem)
            assert keys, case

    if not pem:
        root, base_url = key_server
        fname = case.replace(".yaml", ".jwk")
        (root / fname).write_text(key)
        remote = RemoteJWKS(url=f"{base_url}/{fname}")
        if want_err or want_remote_err:
            with pytest.raises(JWTError) as exc:
                remote.keys()
            assert (want_err or want_remote_err) in str(exc.value), case
        else:
            assert remote.keys(), case


def _load_local(key: str, pem: bool):
    return parse_key_material(key.encode(), pem=pem)


def test_load_keyset_roundtrip(tmp_path):
    """load_keyset consumes the same material through the config surface."""
    with open(os.path.join(CORPUS, "single_key.rsa.rs256.yaml"), encoding="utf-8") as f:
        tc = yaml.safe_load(f)
    ks = load_keyset(
        {"id": "k", "local": {"data": base64.b64encode(tc["input"]["key"].encode()).decode()}}
    )
    assert len(ks.keys) == 1
    assert ks.keys[0].kid == "cerbos-test"
    assert ks.keys[0].alg == "RS256"
