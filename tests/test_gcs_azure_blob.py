"""GCS and Azure blob transports: fake-server sync tests + signing vector.

Mirrors tests/test_s3_blob.py's pattern for the two other object stores the
reference supports via gocloud (internal/storage/blob): minimal local fake
servers speaking the GCS JSON API and the Azure Blob XML API drive the full
BlobStore clone loop (list, conditional download by etag, deletion of
vanished keys), plus a known-answer test for the Azure Shared Key signature
construction.
"""

import base64
import http.server
import json
import threading
import urllib.parse

import pytest

from cerbos_tpu.storage.azure_blob import shared_key_signature
from cerbos_tpu.storage.blob import BlobStore
from cerbos_tpu.storage.gcs import GCSClient

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
"""


class _FakeGCS(http.server.ThreadingHTTPServer):
    def __init__(self):
        self.objects: dict[str, bytes] = {}
        self.requests: list[str] = []
        super().__init__(("127.0.0.1", 0), _GCSHandler)


class _GCSHandler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):  # noqa: D102
        pass

    def do_GET(self):
        srv: _FakeGCS = self.server  # type: ignore[assignment]
        srv.requests.append(self.path)
        parsed = urllib.parse.urlparse(self.path)
        parts = parsed.path.split("/")
        # /storage/v1/b/{bucket}/o or /storage/v1/b/{bucket}/o/{object}
        if parsed.path.startswith("/storage/v1/b/") and parts[5:6] == ["o"] and len(parts) == 6:
            q = urllib.parse.parse_qs(parsed.query)
            prefix = q.get("prefix", [""])[0]
            items = [
                {"name": k, "md5Hash": base64.b64encode(v[:8]).decode(), "size": len(v)}
                for k, v in sorted(srv.objects.items())
                if k.startswith(prefix)
            ]
            # one-item pages to exercise pagination
            token = q.get("pageToken", [""])[0]
            start = int(token) if token else 0
            body: dict = {"items": items[start : start + 1]}
            if start + 1 < len(items):
                body["nextPageToken"] = str(start + 1)
            payload = json.dumps(body).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(payload)
            return
        if parsed.path.startswith("/storage/v1/b/") and len(parts) >= 7:
            key = urllib.parse.unquote(parts[6])
            data = srv.objects.get(key)
            if data is None:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.end_headers()
            self.wfile.write(data)
            return
        self.send_response(404)
        self.end_headers()


class _FakeAzure(http.server.ThreadingHTTPServer):
    def __init__(self):
        self.objects: dict[str, bytes] = {}
        self.auth_headers: list[str] = []
        super().__init__(("127.0.0.1", 0), _AzureHandler)


class _AzureHandler(http.server.BaseHTTPRequestHandler):
    def log_message(self, *a):  # noqa: D102
        pass

    def do_GET(self):
        srv: _FakeAzure = self.server  # type: ignore[assignment]
        srv.auth_headers.append(self.headers.get("Authorization", ""))
        parsed = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(parsed.query)
        if q.get("comp") == ["list"]:
            prefix = q.get("prefix", [""])[0]
            names = sorted(k for k in srv.objects if k.startswith(prefix))
            marker = q.get("marker", [""])[0]
            start = int(marker) if marker else 0
            page = names[start : start + 2]
            blobs = "".join(
                f"<Blob><Name>{n}</Name><Properties><Etag>{len(srv.objects[n])}-et</Etag>"
                f"<Content-Length>{len(srv.objects[n])}</Content-Length></Properties></Blob>"
                for n in page
            )
            next_marker = str(start + 2) if start + 2 < len(names) else ""
            body = (
                f"<?xml version='1.0'?><EnumerationResults><Blobs>{blobs}</Blobs>"
                f"<NextMarker>{next_marker}</NextMarker></EnumerationResults>"
            ).encode()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body)
            return
        key = urllib.parse.unquote(parsed.path.split("/", 2)[2]) if parsed.path.count("/") >= 2 else ""
        data = srv.objects.get(key)
        if data is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.end_headers()
        self.wfile.write(data)


@pytest.fixture
def fake_gcs():
    srv = _FakeGCS()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


@pytest.fixture
def fake_azure():
    srv = _FakeAzure()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def test_gcs_client_list_get_paginated(fake_gcs):
    fake_gcs.objects = {"p/a.yaml": b"a: 1", "p/b.yaml": b"b: 2", "p/c.yaml": b"c: 3"}
    c = GCSClient(
        bucket="bkt",
        endpoint_url=f"http://127.0.0.1:{fake_gcs.server_address[1]}",
        access_token="tok",
    )
    objs = c.list_objects("p/")
    assert [o.key for o in objs] == ["p/a.yaml", "p/b.yaml", "p/c.yaml"]
    assert c.get_object("p/b.yaml") == b"b: 2"


def test_gcs_blob_store_sync(fake_gcs, tmp_path):
    fake_gcs.objects = {"policies/doc.yaml": POLICY.encode()}
    store = BlobStore(
        bucket_url="gs://bkt",
        work_dir=str(tmp_path / "clone"),
        update_poll_interval=0,
        endpoint_url=f"http://127.0.0.1:{fake_gcs.server_address[1]}",
        prefix="policies/",
    )
    try:
        assert [p.fqn() for p in store.get_all()] == ["cerbos.resource.doc.vdefault"]
        # deletion propagates on the next sync
        fake_gcs.objects.clear()
        events = store.sync_and_compare()
        assert events and store.get_all() == []
    finally:
        store.close()


def test_azure_client_and_store(fake_azure, tmp_path):
    fake_azure.objects = {
        "ctr/policies/doc.yaml": POLICY.encode(),
        "ctr/policies/extra.txt": b"ignored",
    }
    store = BlobStore(
        bucket_url="azblob://acct/ctr",
        work_dir=str(tmp_path / "clone"),
        update_poll_interval=0,
        endpoint_url=f"http://127.0.0.1:{fake_azure.server_address[1]}",
        prefix="ctr/policies/",
        access_key=base64.b64encode(b"secret-key").decode(),
    )
    try:
        assert [p.fqn() for p in store.get_all()] == ["cerbos.resource.doc.vdefault"]
        # SharedKey auth header was sent on every request
        assert fake_azure.auth_headers and all(
            h.startswith("SharedKey acct:") for h in fake_azure.auth_headers
        )
    finally:
        store.close()


def test_azure_shared_key_vector():
    """Known-answer vector: deterministic inputs → stable signature, so any
    change to the canonicalization breaks loudly."""
    sig = shared_key_signature(
        account="acct",
        key_b64=base64.b64encode(b"0123456789abcdef").decode(),
        verb="GET",
        path="/ctr",
        query={"comp": "list", "restype": "container"},
        headers={"x-ms-date": "Mon, 01 Jan 2024 00:00:00 GMT", "x-ms-version": "2021-08-06"},
    )
    assert sig == "y3p0L8L0oJruSKnxKkNp0INVNJEhQmu4Gh7rhi88kDc="
