"""Host-predicate batch grouping must never collapse CEL-distinct values.

The packer groups inputs by the device key encoding (tag, hi, lo, sid, nan,
subtype) of each predicate's referenced paths and evaluates once per group.
The double key is lossy for big ints (2^53 vs 2^53+1) and erases the
int-vs-double distinction (1 vs 1.0) — the subtype column must keep those
apart (or exclude them from grouping) so grouped results stay bit-exact with
the per-input oracle.
"""

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.ruletable.check import check_input
from cerbos_tpu.tpu import TpuEvaluator

# string(...)+contains keeps the condition on the host-predicate path while
# the referenced value is numeric — exactly the lossy-key scenario
POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: "default"
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: string(R.attr.n).contains("9007199254740993")
"""


def _inputs(values):
    return [
        CheckInput(
            request_id=f"r{i}",
            principal=Principal(id="u", roles=["user"], attr={}),
            resource=Resource(kind="doc", id=f"d{i}", attr={"n": v}),
            actions=["read"],
        )
        for i, v in enumerate(values)
    ]


@pytest.fixture(scope="module")
def ev():
    # without the fused native entry point the grouped path under test never
    # runs and every assertion would pass vacuously
    from cerbos_tpu import native

    mod = native.get()
    if mod is None or not hasattr(mod, "encode_attr_column"):
        pytest.skip("native encode_attr_column unavailable — grouped pred path can't be exercised")
    rt = build_rule_table(compile_policy_set(list(parse_policies(POLICY))))
    return TpuEvaluator(rt, use_jax=False, min_device_batch=1)


def _assert_oracle_parity(ev, inputs):
    params = EvalParams()
    outs = ev.check(inputs, params)
    for inp, out in zip(inputs, outs):
        oracle = check_input(ev.rule_table, inp, params, None)
        assert {a: e.effect for a, e in out.actions.items()} == {
            a: e.effect for a, e in oracle.actions.items()
        }, inp.resource.attr


def test_big_int_values_not_collapsed(ev):
    # 2^53 and 2^53+1 share a double key; results must still differ
    _assert_oracle_parity(ev, _inputs([9007199254740993 if i % 2 == 0 else 9007199254740992 for i in range(64)]))


def test_int_vs_float_not_collapsed(ev):
    _assert_oracle_parity(ev, _inputs([1 if i % 2 == 0 else 1.0 for i in range(64)]))


def test_container_values_fall_back(ev):
    # lists at the referenced path are TAG_OTHER: never grouped
    _assert_oracle_parity(ev, _inputs([[1, 2] if i % 3 == 0 else "x" for i in range(48)]))
