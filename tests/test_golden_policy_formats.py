"""Reference policy_formats corpus: YAML/JSON parse equivalence.

Mirrors internal/policy/io_test.go: every policy parses identically from its
.yaml and .json renderings (TestReadPolicy/TestHash), and single-policy
reads reject multi-document files while tolerating trailing whitespace and
comment-only documents (TestReadFileWithMultiplePolicies).
"""

import os

import pytest

from cerbos_tpu.policy.parser import ParseError, parse_policies, parse_policy_file

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "policy_formats")

PAIRS = sorted(
    f[:-5] for f in os.listdir(CORPUS)
    if f.endswith(".yaml") and os.path.exists(os.path.join(CORPUS, f[:-5] + ".json"))
)


@pytest.mark.parametrize("name", PAIRS)
def test_yaml_json_equivalence(name):
    with open(os.path.join(CORPUS, name + ".yaml"), encoding="utf-8") as f:
        yaml_pols = list(parse_policies(f.read(), source="x"))
    with open(os.path.join(CORPUS, name + ".json"), encoding="utf-8") as f:
        json_pols = list(parse_policies(f.read(), source="x"))
    assert len(yaml_pols) == len(json_pols) == 1
    # model dataclass equality (source_file/positions excluded via compare=False;
    # equal models imply equal deterministic hashes — the TestHash analogue)
    assert yaml_pols[0] == json_pols[0], name


@pytest.mark.parametrize(
    "name,want_err",
    [
        ("multiple_policies.yaml", True),
        ("single_policy_trailing_spaces.yaml", False),
        ("single_policy_others_commented.yaml", False),
    ],
)
def test_single_policy_reads(name, want_err):
    path = os.path.join(CORPUS, name)
    if want_err:
        with pytest.raises(ParseError, match="expected exactly one policy"):
            parse_policy_file(path)
    else:
        pol = parse_policy_file(path)
        assert pol.kind
