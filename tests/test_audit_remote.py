"""Remote audit ingest: batching, retry/backoff, drop-oldest, auth header."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from cerbos_tpu.audit.remote import RemoteIngestBackend


class _IngestServer:
    def __init__(self):
        self.batches = []
        self.fail = False
        self.auth_headers = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                outer.auth_headers.append(self.headers.get("Authorization"))
                body = self.rfile.read(int(self.headers.get("Content-Length", "0")))
                if outer.fail:
                    self.send_error(503)
                    return
                outer.batches.append(json.loads(body)["entries"])
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()


@pytest.fixture()
def ingest():
    srv = _IngestServer()
    yield srv
    srv.stop()


def _wait(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_batched_flush_and_auth(ingest):
    be = RemoteIngestBackend(
        endpoint=f"http://127.0.0.1:{ingest.port}/ingest",
        auth_token="tok-123",
        batch_size=4,
        flush_interval_s=0.2,
    )
    for i in range(10):
        be.write({"callId": f"c{i}", "kind": "decision"})
    assert _wait(lambda: sum(len(b) for b in ingest.batches) == 10)
    assert all(len(b) <= 4 for b in ingest.batches)
    assert ingest.auth_headers[0] == "Bearer tok-123"
    be.close()


def test_retry_after_failure_preserves_entries(ingest):
    ingest.fail = True
    be = RemoteIngestBackend(
        endpoint=f"http://127.0.0.1:{ingest.port}/ingest",
        batch_size=2,
        flush_interval_s=0.1,
        backoff_base_s=0.05,
        backoff_max_s=0.2,
    )
    be.write({"callId": "a"})
    be.write({"callId": "b"})
    assert _wait(lambda: be.stats["failures"] >= 2)
    assert ingest.batches == []  # nothing committed
    ingest.fail = False
    assert _wait(lambda: be.stats["posted"] == 2)
    assert [e["callId"] for e in ingest.batches[0]] == ["a", "b"]  # nothing lost
    be.close()


def test_drop_oldest_past_buffer(ingest):
    ingest.fail = True
    be = RemoteIngestBackend(
        endpoint=f"http://127.0.0.1:{ingest.port}/ingest",
        batch_size=100,
        flush_interval_s=5.0,
        max_buffer=5,
        backoff_base_s=5.0,
    )
    for i in range(8):
        be.write({"callId": f"c{i}"})
    assert be.stats["dropped"] == 3
    with be._lock:
        kept = [e["callId"] for e in be._buf]
    assert kept == ["c3", "c4", "c5", "c6", "c7"]
    be.close()


def test_audit_log_integration(ingest):
    from cerbos_tpu.audit.log import new_audit_log
    import cerbos_tpu.audit.remote  # noqa: F401  (registers the backend)

    log = new_audit_log(
        {
            "enabled": True,
            "backend": "remote",
            "remote": {
                "endpoint": f"http://127.0.0.1:{ingest.port}/ingest",
                "batchSize": 2,
                "flushIntervalSeconds": 0.1,
            },
        }
    )
    assert log is not None
    log.write_access("call-x", "/cerbos.svc.v1.CerbosService/CheckResources", peer="1.2.3.4")
    log.close()
    assert _wait(lambda: sum(len(b) for b in ingest.batches) >= 1)
