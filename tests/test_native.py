"""Native extension: build, load, differential vs pure Python."""

import random
import string
import struct

import pytest

from cerbos_tpu import native
from cerbos_tpu.globs import _py_matches_glob
from cerbos_tpu.tpu.columns import double_key, split_key


@pytest.fixture(scope="module")
def mod():
    m = native.get()
    if m is None:
        pytest.skip("native extension unavailable (no g++?)")
    return m


PATTERNS = [
    "*", "**", "view", "view:*", "view:**", "*:public", "a?c", "[vV]iew",
    "[!v]iew", "{view,edit}", "{view,edit}:*", "v[a-z]ew", "a\\*b", "",
    "view:*:deep", "**:end", "{a,{b,c}}x", "[0-9]*",
]
VALUES = [
    "view", "view:public", "view:public:extra", "edit:doc", "abc", "a:c",
    "View", "a*b", "", "view:x:deep", "anything:at:end", "bx", "cx", "ax",
    "9abc", "view:",
]


class TestGlobDifferential:
    def test_matrix(self, mod):
        for pat in PATTERNS:
            for val in VALUES:
                want = _py_matches_glob(pat, val)
                got = mod.glob_match(pat, val)
                assert got == want, f"pattern={pat!r} value={val!r}: native={got} python={want}"

    def test_random_fuzz(self, mod):
        rng = random.Random(99)
        alphabet = "ab:*?[]{}\\-!" + string.ascii_lowercase[:4]
        for _ in range(3000):
            pat = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 8)))
            val = "".join(rng.choice("ab:cd") for _ in range(rng.randint(0, 8)))
            want = _py_matches_glob(pat, val)
            got = mod.glob_match(pat, val)
            assert got == want, f"pattern={pat!r} value={val!r}: native={got} python={want}"

    def test_match_many(self, mod):
        idx = mod.glob_match_many(PATTERNS, "view:public")
        want = [i for i, p in enumerate(PATTERNS) if _py_matches_glob(p, "view:public")]
        assert idx == want


class TestEncodeDoubleKeys:
    def test_negative_zero_equals_zero(self, mod):
        buf = struct.pack("<2d", 0.0, -0.0)
        hi_b, lo_b, _ = mod.encode_double_keys(buf)
        his = struct.unpack("<2i", hi_b)
        los = struct.unpack("<2i", lo_b)
        assert (his[0], los[0]) == (his[1], los[1])
        assert split_key(double_key(0.0)) == split_key(double_key(-0.0))

    def test_matches_python_encoding(self, mod):
        values = [0.0, -0.0, 1.0, -1.0, 3.14, -2.5e300, 2.5e-300, float("inf"), float("-inf"), float("nan"), 42.0]
        buf = struct.pack(f"<{len(values)}d", *values)
        hi_b, lo_b, nan_b = mod.encode_double_keys(buf)
        his = struct.unpack(f"<{len(values)}i", hi_b)
        los = struct.unpack(f"<{len(values)}i", lo_b)
        nans = list(nan_b)
        for i, v in enumerate(values):
            if v != v:
                assert nans[i] == 1
                continue
            want_hi, want_lo = split_key(double_key(v))
            assert (his[i], los[i]) == (want_hi, want_lo), f"value {v}"

    def test_order_preserved_signed_compare(self, mod):
        # the device compares (hi, lo) as SIGNED int32 pairs; the sign-biased
        # encoding must make that ordering equal the double ordering
        rng = random.Random(5)
        values = sorted(
            [rng.uniform(-1e6, 1e6) for _ in range(100)]
            + [0.0, -0.0, 1e-308, -1e-308, 1e308, -1e308, 0.5, -0.5]
        )
        buf = struct.pack(f"<{len(values)}d", *values)
        hi_b, lo_b, _ = mod.encode_double_keys(buf)
        his = struct.unpack(f"<{len(values)}i", hi_b)
        los = struct.unpack(f"<{len(values)}i", lo_b)
        keys = list(zip(his, los))  # plain signed tuple comparison
        assert keys == sorted(keys)
        # and the python encoder agrees
        for v, k in zip(values, keys):
            assert split_key(double_key(v)) == k


class TestReviewRegressions:
    def test_comma_inside_class_in_alternates(self, mod):
        # commas inside [...] are not alternate separators
        assert mod.glob_match("{[a,b]x,c}", "ax") == _py_matches_glob("{[a,b]x,c}", "ax")
        assert mod.glob_match("{[a,b]x,c}", "c") is True
        assert mod.glob_match("{[a,b]x,c}", ",x") == _py_matches_glob("{[a,b]x,c}", ",x")

    def test_non_ascii_routes_to_python(self):
        from cerbos_tpu.globs import matches_glob

        # '?' must consume one character, not one UTF-8 byte
        assert matches_glob("u?x", "uéx") is True
        assert matches_glob("é*", "était") is True

    def test_trailing_newline_exact_match(self):
        from cerbos_tpu.globs import matches_glob

        assert not _py_matches_glob("a", "a\n")
        assert not matches_glob("a", "a\n")


class TestGlobBraceClassAgreement:
    """Native and Python matchers must agree on '[' / ']' inside '{...}'."""

    CASES = ["{a],b}", "{a[,b}", "{[a,b]x,c}", "{a[}b],c}", "{a\\,b,c}"]
    VALS = ["a]", "b", "c", "ax", ",x", "a,b", "{a[,b}", "a}b]"]

    def test_agreement(self, mod):
        for pat in self.CASES:
            for val in self.VALS:
                assert mod.glob_match(pat, val) == _py_matches_glob(pat, val), (pat, val)

    def test_fuzz_with_commas(self, mod):
        rng = random.Random(123)
        alphabet = "ab:,*?[]{}\\-!c"
        for _ in range(3000):
            pat = "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 10)))
            val = "".join(rng.choice("ab:c,]") for _ in range(rng.randint(0, 8)))
            assert mod.glob_match(pat, val) == _py_matches_glob(pat, val), (pat, val)
