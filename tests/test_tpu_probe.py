"""TPU probe: subprocess isolation, evidence capture, summary shape."""

import json

from cerbos_tpu.util import tpu_probe


def test_run_probe_succeeds_on_cpu_env():
    # under the test conftest the axon plugin is scrubbed and
    # JAX_PLATFORMS=cpu, so the probe subprocess initializes jax quickly
    r = tpu_probe._run_probe({}, timeout_s=120.0, hang_after=110.0)
    assert r["ok"] is True
    assert r["rc"] == 0
    assert "PLATFORM cpu" in r["stdout_tail"]
    assert tpu_probe._parse_platform(r["stdout_tail"]) == "cpu"


def test_run_probe_captures_failure_evidence():
    # an impossible platform fails fast with a captured error message
    r = tpu_probe._run_probe({"JAX_PLATFORMS": "nonexistent"}, timeout_s=120.0, hang_after=110.0)
    assert r["ok"] is False
    assert r["rc"] not in (0, None)
    assert r["stderr_tail"]  # the why is recorded, not swallowed


def test_summarize_classifies_rungs():
    result = {
        "available": False,
        "platform": None,
        "rungs": [
            {"rung": "axon-attempt-1", "ok": False, "rc": None, "timed_out": True,
             "duration_s": 90.0, "stdout_tail": "", "stderr_tail": ""},
            {"rung": "axon-attempt-2", "ok": False, "rc": 1, "timed_out": False,
             "duration_s": 60.0, "stdout_tail": "",
             "stderr_tail": "Timeout (0:01:00)!\nThread ..."},
            {"rung": "libtpu-direct", "ok": False, "rc": 1, "timed_out": False,
             "duration_s": 2.0, "stdout_tail": "", "stderr_tail": "RuntimeError: no device"},
        ],
    }
    s = tpu_probe.summarize(result)
    assert s["available"] is False
    kinds = [r["result"] for r in s["rungs"]]
    assert kinds == ["hang", "hang", "exit-1"]


def test_artifact_roundtrip(tmp_path):
    result = {"available": True, "platform": "cpu", "rungs": []}
    path = tmp_path / "probe.json"
    tpu_probe.write_artifact(result, str(path))
    assert json.loads(path.read_text()) == result
