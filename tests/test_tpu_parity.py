"""Differential parity: TPU evaluator vs CPU oracle.

The reference's golden corpus strategy (SURVEY.md §4 tier 1) plus the
property-based differential fuzzer the reference lacks: random policies and
requests, CPU oracle vs device path, effects must match exactly.
"""

import random

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu import TpuEvaluator

import test_engine_check as corpus


MODES = ["numpy", "jax", "mesh8"]


def _make_evaluator(rule_table, params, mode):
    kwargs = {}
    if mode == "mesh8":
        from cerbos_tpu.parallel.mesh import make_mesh

        kwargs["mesh"] = make_mesh(8)
    return TpuEvaluator(
        rule_table,
        globals_=params.globals,
        use_jax=mode != "numpy",
        min_device_batch=0,
        **kwargs,
    )


def assert_parity(rule_table, inputs, params=None, use_jax=False, mode=None):
    params = params or EvalParams()
    if mode is None:
        mode = "jax" if use_jax else "numpy"
    ev = _make_evaluator(rule_table, params, mode)
    got = ev.check(inputs, params)
    want = [check_input(rule_table, i, params) for i in inputs]
    for i, (g, w) in enumerate(zip(got, want)):
        assert {a: (e.effect, e.policy, e.scope) for a, e in g.actions.items()} == {
            a: (e.effect, e.policy, e.scope) for a, e in w.actions.items()
        }, f"effect mismatch for input {i}: {inputs[i]}"
        assert g.effective_derived_roles == w.effective_derived_roles, f"edr mismatch for input {i}"
        assert g.effective_policies == w.effective_policies, (
            f"effective_policies mismatch for input {i}: {g.effective_policies} vs {w.effective_policies}"
        )
        assert sorted((o.src, o.action, repr(o.val)) for o in g.outputs) == sorted(
            (o.src, o.action, repr(o.val)) for o in w.outputs
        ), f"outputs mismatch for input {i}"
    return ev


def table_for(src):
    return build_rule_table(compile_policy_set(list(parse_policies(src))))


CORPORA = {
    "main": corpus.POLICIES,
    "scoped": corpus.SCOPED_POLICIES,
    "rpc": corpus.RPC_POLICIES,
    "role_policies": corpus.ROLE_POLICIES,
    "variables": corpus.VARIABLES_POLICIES,
}


def corpus_inputs():
    P, R = corpus.P, corpus.R
    return {
        "main": [
            CheckInput(principal=P(), resource=R(attr={"owner": "john"}), actions=["view:public", "approve", "create"]),
            CheckInput(principal=P(), resource=R(attr={"owner": "sally"}), actions=["view:public"]),
            CheckInput(principal=P(id="boss", roles=["manager"]), resource=R(attr={"managerId": "boss", "status": "PENDING_APPROVAL"}), actions=["approve"]),
            CheckInput(principal=P(id="boss", roles=["manager"]), resource=R(attr={"managerId": "boss", "status": "DRAFT"}), actions=["approve"]),
            CheckInput(principal=P(id="daffy", roles=["manager"]), resource=R(attr={"managerId": "daffy", "status": "PENDING_APPROVAL"}), actions=["approve"]),
            CheckInput(principal=P(id="daffy", roles=["employee"]), resource=R(kind="secret_files"), actions=["view"]),
            CheckInput(principal=P(id="x", roles=["auditor", "admin"]), resource=R(), actions=["delete", "view:x"]),
            CheckInput(principal=P(id="ghost", roles=["nobody"]), resource=R(kind="bogus"), actions=["view"]),
        ],
        "scoped": [
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc", scope="acme.hr"), actions=["view", "edit", "delete"]),
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc", scope="acme.hr", attr={"confidential": True}), actions=["view"]),
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc", scope="acme"), actions=["delete"]),
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc"), actions=["view", "delete"]),
        ],
        "rpc": [
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc", scope="tenant", attr={"public": True}), actions=["view", "edit"]),
            CheckInput(principal=P(id="u", roles=["user"]), resource=R(kind="doc", scope="tenant", attr={"public": False}), actions=["view"]),
        ],
        "role_policies": [
            CheckInput(principal=P(id="i1", roles=["intern"]), resource=R(kind="doc", scope="acme"), actions=["view", "edit", "delete"]),
            CheckInput(principal=P(id="c1", roles=["contractor"]), resource=R(kind="doc", scope="acme", attr={"assigned": "c1"}), actions=["edit", "share"]),
            CheckInput(principal=P(id="c1", roles=["contractor"]), resource=R(kind="doc", scope="acme", attr={"assigned": "zz"}), actions=["edit"]),
            CheckInput(principal=P(id="a", roles=["admin"]), resource=R(kind="doc", scope="acme"), actions=["delete"]),
        ],
        "variables": [
            CheckInput(principal=P(id="u", roles=["user"], attr={"dept": "eng"}), resource=R(kind="report", attr={"flagged": False}), actions=["view"]),
            CheckInput(principal=P(id="u", roles=["user"], attr={"dept": "sales"}), resource=R(kind="report", attr={"flagged": False}), actions=["view"]),
            CheckInput(principal=P(id="u", roles=["user"], attr={"dept": "eng"}), resource=R(kind="report", attr={"flagged": True}), actions=["view"]),
        ],
    }


@pytest.mark.parametrize("name", sorted(CORPORA))
@pytest.mark.parametrize("mode", MODES)
def test_corpus_parity(name, mode):
    rt = table_for(CORPORA[name])
    ev = assert_parity(rt, corpus_inputs()[name], mode=mode)
    # the corpora are designed to be device-evaluable
    assert ev.stats["device_inputs"] > 0


FUZZ_POLICIES = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: fuzz_roles
  definitions:
    - name: owner
      parentRoles: [viewer, editor]
      condition:
        match:
          expr: R.attr.owner == P.id
    - name: senior
      parentRoles: [editor]
      condition:
        match:
          expr: P.attr.level >= 5
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: widget
  version: default
  importDerivedRoles: [fuzz_roles]
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [viewer, editor]
    - actions: ["write"]
      effect: EFFECT_ALLOW
      derivedRoles: [owner]
    - actions: ["write"]
      effect: EFFECT_ALLOW
      derivedRoles: [senior]
      condition:
        match:
          any:
            of:
              - expr: R.attr.size < 100
              - expr: R.attr.kind == "small"
    - actions: ["purge"]
      effect: EFFECT_DENY
      roles: ["*"]
      condition:
        match:
          expr: R.attr.protected == true
    - actions: ["purge"]
      effect: EFFECT_ALLOW
      roles: [editor]
---
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: widget
  version: default
  scope: team
  rules:
    - actions: ["read"]
      effect: EFFECT_DENY
      roles: [viewer]
      condition:
        match:
          expr: R.attr.restricted == true
---
apiVersion: api.cerbos.dev/v1
principalPolicy:
  principal: special
  version: default
  rules:
    - resource: widget
      actions:
        - action: "read"
          effect: EFFECT_ALLOW
        - action: "purge"
          effect: EFFECT_DENY
"""


@pytest.mark.parametrize("mode", MODES)
def test_fuzz_parity(mode):
    rng = random.Random(42)
    rt = table_for(FUZZ_POLICIES)
    inputs = []
    for i in range(200):
        roles = rng.sample(["viewer", "editor", "ghost"], k=rng.randint(1, 2))
        pid = rng.choice(["u1", "u2", "special"])
        attr = {}
        if rng.random() < 0.8:
            attr["owner"] = rng.choice(["u1", "u2"])
        if rng.random() < 0.7:
            attr["size"] = rng.choice([10, 99, 100, 1000, 50.5])
        if rng.random() < 0.5:
            attr["kind"] = rng.choice(["small", "big", ""])
        if rng.random() < 0.5:
            attr["protected"] = rng.choice([True, False, "yes", 1])
        if rng.random() < 0.4:
            attr["restricted"] = rng.choice([True, False, None])
        pattr = {}
        if rng.random() < 0.7:
            pattr["level"] = rng.choice([1, 5, 7, "9", 4.9])
        inputs.append(
            CheckInput(
                principal=Principal(id=pid, roles=roles, attr=pattr),
                resource=Resource(
                    kind="widget",
                    id=f"w{i}",
                    attr=attr,
                    scope=rng.choice(["", "team"]),
                ),
                actions=rng.sample(["read", "write", "purge", "zap"], k=rng.randint(1, 3)),
            )
        )
    ev = assert_parity(rt, inputs, mode=mode)
    # most inputs should take the device path
    assert ev.stats["device_inputs"] >= 150, ev.stats


NEGATIVE_NUM_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: ledger
  version: default
  rules:
    - actions: ["post"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.balance > -100.5
    - actions: ["audit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.balance <= 0
"""


@pytest.mark.parametrize("mode", MODES)
def test_negative_number_ordering_parity(mode):
    # regression: sign-biased (hi, lo) key encoding — comparisons must be
    # correct across the positive/negative double boundary
    rt = table_for(NEGATIVE_NUM_POLICIES)
    inputs = []
    for i, bal in enumerate([-1e9, -101.0, -100.5, -100.49, -1.0, -0.0, 0.0, 0.5, 99.0, 1e9, -1e-300, 1e-300]):
        inputs.append(CheckInput(
            principal=Principal(id=f"u{i}", roles=["user"], attr={}),
            resource=Resource(kind="ledger", id=f"l{i}", attr={"balance": bal}),
            actions=["post", "audit"],
        ))
    assert_parity(rt, inputs, mode=mode)


UNCONDITIONAL_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: plain
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
    - actions: ["nuke"]
      effect: EFFECT_DENY
      roles: ["*"]
"""


@pytest.mark.parametrize("mode", MODES)
def test_no_condition_table_parity(mode):
    # regression (ADVICE r1): a table with no attribute/predicate columns must
    # still size the condition matrix to the real batch, not B=1
    rt = table_for(UNCONDITIONAL_POLICIES)
    inputs = [
        CheckInput(
            principal=Principal(id=f"u{i}", roles=["user"], attr={}),
            resource=Resource(kind="plain", id=f"p{i}", attr={}),
            actions=["view", "nuke", "ghost"],
        )
        for i in range(20)
    ]
    assert_parity(rt, inputs, mode=mode)


LIST_MEMBERSHIP_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: article
  version: default
  rules:
    - actions: ["publish"]
      effect: EFFECT_ALLOW
      roles: [author]
      condition:
        match:
          all:
            of:
              - expr: '"cerbos-jwt-tests" in request.aux_data.jwt.aud'
              - expr: '"A" in request.aux_data.jwt.customArray'
    - actions: ["tag"]
      effect: EFFECT_ALLOW
      roles: [author]
      condition:
        match:
          expr: '"featured" in R.attr.labels'
    - actions: ["untag"]
      effect: EFFECT_ALLOW
      roles: [author]
      condition:
        match:
          expr: '!("locked" in R.attr.labels)'
"""


@pytest.mark.parametrize("mode", MODES)
def test_list_membership_device(mode):
    """`const in attr-list` runs on device via sid-list columns — including
    error semantics for missing attrs and non-list values under negation."""
    from cerbos_tpu.engine import AuxData

    rt = table_for(LIST_MEMBERSHIP_POLICIES)
    inputs = []
    label_variants = [
        ["featured", "locked"], ["featured"], ["locked"], [], ["other", 3, True],
        "not-a-list", None, 42,
    ]
    aud_variants = [["cerbos-jwt-tests"], ["other"], [], None]
    for i, labels in enumerate(label_variants):
        for j, aud in enumerate(aud_variants):
            attr = {} if labels is None else {"labels": labels}
            aux = None
            if aud is not None:
                aux = AuxData(jwt={"aud": aud, "customArray": ["A"] if j % 2 == 0 else ["B"]})
            inputs.append(CheckInput(
                principal=Principal(id=f"a{i}{j}", roles=["author"], attr={}),
                resource=Resource(kind="article", id=f"r{i}{j}", attr=attr),
                actions=["publish", "tag", "untag"],
                aux_data=aux,
            ))
    ev = assert_parity(rt, inputs, mode=mode)
    assert ev.stats["device_inputs"] == len(inputs), ev.stats
    # the membership conditions must be device kernels, not host predicates
    assert len(ev.lowered.compiler.preds) == 0, "list membership fell back to predicate columns"


@pytest.mark.parametrize("mode", ["numpy", "jax"])
def test_list_membership_over_map_routes_to_oracle(mode):
    # CEL `in` over a MAP is key membership; the device list column can't
    # express it, so such inputs must take the oracle and still match
    rt = table_for(LIST_MEMBERSHIP_POLICIES)
    inputs = [
        CheckInput(
            principal=Principal(id="m", roles=["author"], attr={}),
            resource=Resource(kind="article", id="m1", attr={"labels": {"featured": 1}}),
            actions=["tag", "untag"],
        ),
        CheckInput(
            principal=Principal(id="m2", roles=["author"], attr={}),
            resource=Resource(kind="article", id="m2", attr={"labels": {"locked": True}}),
            actions=["tag", "untag"],
        ),
    ]
    ev = assert_parity(rt, inputs, mode=mode)
    assert ev.stats["oracle_inputs"] == len(inputs), ev.stats


TS_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: booking
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) > timestamp("2024-06-01T00:00:00Z")
    - actions: ["edit"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) < now()
    - actions: ["cmp"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) <= timestamp(R.attr.endsAt)
    - actions: ["eq"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) == timestamp("2024-06-02T00:00:00+00:00")
    - actions: ["notbefore"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: "!(timestamp(R.attr.startsAt) < timestamp(\\"2024-01-01T00:00:00Z\\"))"
"""


@pytest.mark.parametrize("mode", MODES)
def test_timestamp_conditions_on_device(mode):
    """timestamp(path) comparisons ride device key columns; all value shapes
    (valid RFC3339, offsets, epoch ints, garbage, missing, wrong type) must
    match the oracle, including error absorption under negation."""
    import datetime

    from cerbos_tpu.cel.values import Timestamp

    rt = table_for(TS_POLICIES)
    now = Timestamp.from_datetime(datetime.datetime(2024, 6, 3, tzinfo=datetime.timezone.utc))
    params = EvalParams(now_fn=lambda: now)
    P, R = corpus.P, corpus.R

    starts = [
        "2024-06-02T00:00:00Z",            # between const and now
        "2024-05-01T12:30:00+02:00",       # offset form, before const
        "2031-01-01T00:00:00Z",            # future
        "2024-06-02T00:00:00.000Z",        # fractional-second form of eq const
        "1996-02-27T08:00:00Z",            # before the notbefore cutoff
        "not-a-timestamp",                 # CEL error
        1717286400,                        # int epoch-seconds overload
        12.5,                              # float: no timestamp() overload
        None,                              # null: no overload
    ]
    inputs = []
    for s in starts:
        attr = {"endsAt": "2024-07-01T00:00:00Z"}
        if s is not None:
            attr["startsAt"] = s
        inputs.append(CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind="booking", id="b", attr=attr),
            actions=["view", "edit", "cmp", "eq", "notbefore"],
        ))
    # missing attribute entirely
    inputs.append(CheckInput(
        principal=Principal(id="u", roles=["user"]),
        resource=Resource(kind="booking", id="b", attr={}),
        actions=["view", "edit", "cmp", "eq", "notbefore"],
    ))
    ev = assert_parity(rt, inputs, params=params, mode=mode)
    assert ev.stats["oracle_inputs"] == 0, "timestamp comparisons must stay on device"


STR_ORD_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: shelf
  version: default
  rules:
    - actions: ["browse"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.section >= "m"
    - actions: ["count"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.quantity < 10
"""


@pytest.mark.parametrize("mode", MODES)
def test_string_ordering_and_numeric_type_errors_stay_on_device(mode):
    """String ordering against a constant rides a predicate column (not the
    oracle), and non-numeric values at numeric orderings produce CEL type
    errors on device — neither forces input fallback."""
    rt = table_for(STR_ORD_POLICIES)
    P, R = corpus.P, corpus.R
    inputs = []
    for section, qty in [
        ("music", 5), ("art", 5), ("m", 20), ("z", None), (None, "many"),
        (3.5, 3), (True, True), ("média", 9.99),
    ]:
        attr = {}
        if section is not None:
            attr["section"] = section
        if qty is not None:
            attr["quantity"] = qty
        inputs.append(CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind="shelf", id="s", attr=attr),
            actions=["browse", "count"],
        ))
    ev = assert_parity(rt, inputs, mode=mode)
    assert ev.stats["oracle_inputs"] == 0, "string ordering must not fall back to the oracle"


NOW_ONLY_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: gate
  version: default
  rules:
    - actions: ["enter"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: now() > timestamp("2020-01-01T00:00:00Z")
    - actions: ["mixed"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: timestamp(R.attr.at) < R.attr.deadline
"""


@pytest.mark.parametrize("mode", ["numpy", "jax"])
def test_now_only_condition_gets_now_key(mode):
    """now() compared against a constant with NO timestamp(path) anywhere:
    the batch-constant now key must still be encoded (regression: the
    default zero key decodes to ~1970 and silently flips the decision)."""
    import datetime

    from cerbos_tpu.cel.values import Timestamp

    rt = table_for(NOW_ONLY_POLICY)
    now = Timestamp.from_datetime(datetime.datetime(2024, 6, 3, tzinfo=datetime.timezone.utc))
    params = EvalParams(now_fn=lambda: now)
    inputs = [CheckInput(
        principal=Principal(id="u", roles=["user"]),
        resource=Resource(kind="gate", id="g", attr={"at": "2024-01-01T00:00:00Z", "deadline": "x"}),
        actions=["enter", "mixed"],
    )]
    ev = assert_parity(rt, inputs, params=params, mode=mode)
    got = ev.check(inputs, params)
    assert got[0].actions["enter"].effect == "EFFECT_ALLOW"  # 2024 > 2020
    # the mixed ts-vs-untyped comparison fell back to a predicate, not an
    # orphaned ts column: no ts path may be registered for it
    assert ("resource", "attr", "deadline") not in ev.lowered.ts_paths


TS_FUZZ_POLICIES = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: event
  version: default
  rules:
    - actions: ["rsvp"]
      effect: EFFECT_ALLOW
      roles: [member]
      condition:
        match:
          all:
            of:
              - expr: timestamp(R.attr.startsAt) > now()
              - expr: R.attr.venue >= "m" || "vip" in R.attr.tags
    - actions: ["recap"]
      effect: EFFECT_ALLOW
      roles: [member]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) <= timestamp(R.attr.endsAt) && !(timestamp(R.attr.endsAt) > now())
    - actions: ["archive"]
      effect: EFFECT_DENY
      roles: ["*"]
      condition:
        match:
          expr: timestamp(R.attr.startsAt) > timestamp("2030-01-01T00:00:00Z")
"""


@pytest.mark.parametrize("mode", MODES)
def test_fuzz_timestamp_string_list_parity(mode):
    """Random mixes over the round-3 device features — timestamp key
    columns, now(), string-ordering predicates, list membership — including
    malformed/missing values, must match the oracle exactly."""
    import datetime

    from cerbos_tpu.cel.values import Timestamp

    rng = random.Random(7)
    rt = table_for(TS_FUZZ_POLICIES)
    now = Timestamp.from_datetime(datetime.datetime(2025, 6, 1, tzinfo=datetime.timezone.utc))
    params = EvalParams(now_fn=lambda: now)
    ts_pool = [
        "2024-01-01T00:00:00Z", "2025-06-01T00:00:00Z", "2025-06-01T00:00:01Z",
        "2031-05-05T10:00:00+02:00", "1999-12-31T23:59:59.999Z",
        "garbage", 1717286400, None, 3.5, True,
    ]
    inputs = []
    for i in range(200):
        attr = {}
        s = rng.choice(ts_pool)
        e = rng.choice(ts_pool)
        if s is not None:
            attr["startsAt"] = s
        if e is not None:
            attr["endsAt"] = e
        if rng.random() < 0.7:
            attr["venue"] = rng.choice(["metro hall", "annex", "zoo", "", 42])
        if rng.random() < 0.6:
            attr["tags"] = rng.choice([["vip"], ["open", "vip"], ["open"], [], "vip", [1, "vip"]])
        inputs.append(CheckInput(
            principal=Principal(id=f"p{i%5}", roles=rng.sample(["member", "guest"], k=rng.randint(1, 2))),
            resource=Resource(kind="event", id=f"e{i}", attr=attr),
            actions=rng.sample(["rsvp", "recap", "archive"], k=rng.randint(1, 3)),
        ))
    ev = assert_parity(rt, inputs, params=params, mode=mode)
    assert ev.stats["device_inputs"] >= 150, ev.stats


def test_submit_collect_matches_check():
    """Streaming submit/collect must return exactly what check() returns,
    in order, with overlapping in-flight batches."""
    from cerbos_tpu.compile import compile_policy_set
    from cerbos_tpu.engine import EvalParams
    from cerbos_tpu.policy.parser import parse_policies
    from cerbos_tpu.ruletable import build_rule_table
    from cerbos_tpu.tpu import TpuEvaluator
    from cerbos_tpu.util import bench_corpus

    rt = build_rule_table(compile_policy_set(list(parse_policies(bench_corpus.corpus_yaml(2)))))
    params = EvalParams()
    ev = TpuEvaluator(rt, use_jax=True, min_device_batch=4)
    batches = [bench_corpus.requests_unique(32, 2, seed=s) for s in (1, 2, 3, 4)]
    want = [ev.check(b, params) for b in batches]
    tickets = [ev.submit(b, params) for b in batches]  # all in flight at once
    got = [ev.collect(t) for t in tickets]
    for wb, gb in zip(want, got):
        for w, g in zip(wb, gb):
            assert w.resource_id == g.resource_id
            assert {a: e.effect for a, e in w.actions.items()} == {
                a: e.effect for a, e in g.actions.items()
            }
    # collect is idempotent
    assert ev.collect(tickets[0]) is got[0]
