"""Bundle format, AuthZen, playground, tracer, telemetry, observability, CLI."""

import json
import os
import subprocess
import sys

import pytest
import yaml

from cerbos_tpu.bundle import BundleStore, build_bundle
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, Engine, Principal, Resource
from cerbos_tpu.storage import DiskStore, new_store

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.owner == P.id
"""


@pytest.fixture()
def policy_dir(tmp_path):
    (tmp_path / "doc.yaml").write_text(POLICY)
    schemas = tmp_path / "_schemas"
    schemas.mkdir()
    (schemas / "doc.json").write_text('{"type": "object"}')
    return tmp_path


class TestBundle:
    def test_roundtrip(self, policy_dir, tmp_path):
        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        manifest = build_bundle(store, out)
        assert manifest.policy_count == 1 and manifest.schema_count == 1

        bstore = BundleStore(out)
        pols = bstore.get_all()
        assert len(pols) == 1
        assert bstore.get_schema("doc.json") == b'{"type": "object"}'

        # a PDP can serve directly from the bundle
        eng = Engine.from_policies(compile_policy_set(pols))
        r = eng.check([CheckInput(principal=Principal(id="u", roles=["user"]),
                                  resource=Resource(kind="doc", id="d", attr={"owner": "u"}),
                                  actions=["view"])])[0]
        assert r.actions["view"].effect == "EFFECT_ALLOW"

    def test_corruption_detected(self, policy_dir, tmp_path):
        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        build_bundle(store, out)
        import gzip

        data = bytearray(gzip.open(out, "rb").read())
        # flip a byte inside a policy entry (not the tar structure)
        idx = data.find(b"EFFECT_ALLOW")
        data[idx:idx + 12] = b"EFFECT_DENYY"
        with gzip.open(out, "wb") as f:
            f.write(bytes(data))
        with pytest.raises(ValueError, match="checksum"):
            BundleStore(out)

    def test_driver_registry(self, policy_dir, tmp_path):
        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        build_bundle(store, out)
        s = new_store({"driver": "bundle", "bundle": {"path": out}})
        assert len(s.get_all()) == 1

    def test_compiled_ir_fast_path(self, policy_dir, tmp_path):
        """v2 bundles carry the compiled IR; the manager skips recompiling."""
        from cerbos_tpu.ruletable.manager import RuleTableManager

        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        manifest = build_bundle(store, out)
        assert manifest.compiled_checksum

        # the IR is a structured encoding (no code execution), so loading it
        # from an untrusted bundle is safe and happens by default
        bstore = BundleStore(out)
        compiled = bstore.get_compiled()
        assert compiled is not None and len(compiled) == 1

        mgr = RuleTableManager(bstore)
        eng = Engine(mgr.rule_table)
        r = eng.check([CheckInput(principal=Principal(id="u", roles=["user"]),
                                  resource=Resource(kind="doc", id="d", attr={"owner": "u"}),
                                  actions=["view"])])[0]
        assert r.actions["view"].effect == "EFFECT_ALLOW"

    def test_compiled_ir_version_gate(self, policy_dir, tmp_path, monkeypatch):
        """Compiler-version mismatch ignores the IR and recompiles sources."""
        import cerbos_tpu.bundle as bundle_mod

        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        build_bundle(store, out)
        monkeypatch.setattr(bundle_mod, "COMPILER_VERSION", "cerbos-tpu-ir-999")
        bstore = BundleStore(out)
        assert bstore.get_compiled() is None  # gated out
        assert len(bstore.get_all()) == 1  # sources still serve

    def test_signed_bundle(self, policy_dir, tmp_path):
        """A configured signing key gates the IR on HMAC authenticity; an
        unsigned load still works (the decode itself is safe)."""
        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        build_bundle(store, out, signing_key=b"k1")
        assert BundleStore(out, signing_key=b"k1").get_compiled() is not None
        assert BundleStore(out, signing_key=b"wrong").get_compiled() is None
        assert BundleStore(out).get_compiled() is not None

    def test_source_only_bundle(self, policy_dir, tmp_path):
        store = DiskStore(str(policy_dir))
        out = str(tmp_path / "b.crbp")
        manifest = build_bundle(store, out, include_compiled=False)
        assert manifest.compiled_checksum == ""
        bstore = BundleStore(out)
        assert bstore.get_compiled() is None
        assert len(bstore.get_all()) == 1


class TestBlobStore:
    def test_file_bucket(self, policy_dir, tmp_path_factory):
        work = tmp_path_factory.mktemp("blob-work")
        s = new_store({"driver": "blob", "blob": {
            "bucket": f"file://{policy_dir}", "workDir": str(work), "updatePollInterval": 0,
        }})
        assert len(s.get_all()) == 1
        # update source, re-sync
        (policy_dir / "doc2.yaml").write_text(POLICY.replace("doc", "doc2"))
        os.utime(policy_dir / "doc2.yaml")
        events = s.sync_and_compare()
        assert any(e.policy_fqn.endswith("doc2.vdefault") for e in events)
        s.close()


class TestAuthZen:
    @pytest.fixture()
    def app_client(self, policy_dir, event_loop=None):
        from aiohttp.test_utils import TestClient, TestServer
        from aiohttp import web
        from cerbos_tpu.server.authzen import AuthZenService
        from cerbos_tpu.server.service import CerbosService

        eng = Engine.from_policies(compile_policy_set(DiskStore(str(policy_dir)).get_all()))
        svc = CerbosService(eng)
        app = web.Application()
        AuthZenService(svc).add_http_routes(app)
        return app

    def test_evaluation(self, app_client):
        import asyncio
        from aiohttp.test_utils import TestClient, TestServer

        async def run():
            async with TestClient(TestServer(app_client)) as client:
                resp = await client.post("/access/v1/evaluation", json={
                    "subject": {"type": "user", "id": "u", "properties": {"roles": ["user"]}},
                    "resource": {"type": "doc", "id": "d", "properties": {"owner": "u"}},
                    "action": {"name": "view"},
                })
                body = await resp.json()
                assert body == {"decision": True}
                resp2 = await client.post("/access/v1/evaluation", json={
                    "subject": {"type": "user", "id": "x", "properties": {"roles": ["user"]}},
                    "resource": {"type": "doc", "id": "d", "properties": {"owner": "u"}},
                    "action": {"name": "view"},
                })
                assert (await resp2.json()) == {"decision": False}
                conf = await client.get("/.well-known/authzen-configuration")
                assert "access_evaluation_endpoint" in await conf.json()

        asyncio.run(run())

    app_client = app_client  # keep fixture name


class TestPlayground:
    def test_validate_and_evaluate(self):
        import asyncio
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer
        from cerbos_tpu.server.playground import PlaygroundService

        app = web.Application()
        PlaygroundService().add_http_routes(app)

        async def run():
            async with TestClient(TestServer(app)) as client:
                ok = await client.post("/api/playground/validate", json={
                    "playgroundId": "p1",
                    "files": [{"fileName": "doc.yaml", "contents": POLICY}],
                })
                assert "success" in await ok.json()
                bad = await client.post("/api/playground/validate", json={
                    "playgroundId": "p2",
                    "files": [{"fileName": "doc.yaml", "contents": POLICY.replace("expr: R.attr", "expr: ((R.attr")}],
                })
                assert "failure" in await bad.json()
                ev = await client.post("/api/playground/evaluate", json={
                    "playgroundId": "p3",
                    "files": [{"fileName": "doc.yaml", "contents": POLICY}],
                    "principal": {"id": "u", "roles": ["user"]},
                    "resource": {"kind": "doc", "id": "d", "attr": {"owner": "u"}},
                    "actions": ["view"],
                })
                body = await ev.json()
                assert body["success"]["results"][0]["effect"] == "EFFECT_ALLOW"

        asyncio.run(run())


class TestTracer:
    def test_traced_check(self, policy_dir):
        from cerbos_tpu.ruletable import build_rule_table
        from cerbos_tpu.tracer import traced_check

        rt = build_rule_table(compile_policy_set(DiskStore(str(policy_dir)).get_all()))
        out, rec = traced_check(rt, CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind="doc", id="d", attr={"owner": "u"}),
            actions=["view"],
        ))
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        events = rec.to_json()
        assert any(e.get("event", {}).get("status") == "ACTIVATED" for e in events)


class TestTelemetry:
    def test_opt_out(self, monkeypatch, tmp_path):
        from cerbos_tpu.telemetry import Telemetry, telemetry_enabled

        assert not telemetry_enabled({"disabled": True})
        monkeypatch.setenv("DO_NOT_TRACK", "1")
        assert not telemetry_enabled({"disabled": False})
        monkeypatch.delenv("DO_NOT_TRACK")
        assert telemetry_enabled({"disabled": False})
        t = Telemetry({"disabled": False}, state_dir=str(tmp_path))
        t.record("server_start")
        assert t._events and t.instance_id
        t.close()


class TestObservability:
    def test_spans_nest(self):
        from cerbos_tpu import observability as obs

        captured = []

        class Cap(obs.SpanExporter):
            def export(self, span, duration_ms):
                captured.append((span.name, span.parent_id, span.trace_id))

        obs.set_exporter(Cap())
        with obs.start_span("outer") as outer:
            with obs.start_span("inner"):
                pass
        obs.set_exporter(obs.SpanExporter())
        names = [c[0] for c in captured]
        assert names == ["inner", "outer"]
        assert captured[0][1] == outer.span_id  # inner's parent
        assert captured[0][2] == captured[1][2]  # same trace


class TestCLI:
    def test_compile_ok_and_fail(self, policy_dir, tmp_path):
        env = {**os.environ, "PYTHONPATH": "/root/repo"}
        r = subprocess.run([sys.executable, "-m", "cerbos_tpu.cli", "compile", str(policy_dir)],
                           capture_output=True, text=True, env=env)
        assert r.returncode == 0, r.stderr
        bad_dir = tmp_path / "bad"
        bad_dir.mkdir()
        (bad_dir / "bad.yaml").write_text(POLICY.replace("expr: R.attr", "expr: (((R.attr"))
        r2 = subprocess.run([sys.executable, "-m", "cerbos_tpu.cli", "compile", str(bad_dir)],
                            capture_output=True, text=True, env=env)
        assert r2.returncode == 3

    def test_compile_runs_tests_exit_4(self, policy_dir):
        (policy_dir / "doc_test.yaml").write_text(yaml.safe_dump({
            "name": "S",
            "tests": [{
                "name": "t",
                "input": {"principals": ["u1"], "resources": ["d1"], "actions": ["view"]},
                "expected": [{"principal": "u1", "resource": "d1", "actions": {"view": "EFFECT_DENY"}}],
            }],
            "principals": {"u1": {"id": "u1", "roles": ["user"]}},
            "resources": {"d1": {"kind": "doc", "id": "d1", "attr": {"owner": "u1"}}},
        }))
        env = {**os.environ, "PYTHONPATH": "/root/repo"}
        r = subprocess.run([sys.executable, "-m", "cerbos_tpu.cli", "compile", str(policy_dir)],
                           capture_output=True, text=True, env=env)
        assert r.returncode == 4, r.stdout + r.stderr

    def test_compilestore_and_healthcheck(self, policy_dir, tmp_path):
        env = {**os.environ, "PYTHONPATH": "/root/repo"}
        out = str(tmp_path / "b.crbp")
        r = subprocess.run([sys.executable, "-m", "cerbos_tpu.cli", "compilestore", str(policy_dir), "-o", out],
                           capture_output=True, text=True, env=env)
        assert r.returncode == 0 and os.path.exists(out), r.stderr
        r2 = subprocess.run([sys.executable, "-m", "cerbos_tpu.cli", "healthcheck", "--host-port", "127.0.0.1:1", "--timeout", "0.5"],
                            capture_output=True, text=True, env=env)
        assert r2.returncode == 1


class TestEmbeddingSDK:
    def test_embedded(self, policy_dir):
        from cerbos_tpu.serve import embedded

        pdp = embedded(policy_dir=str(policy_dir), overrides=["engine.tpu.enabled=false"])
        out = pdp.check([CheckInput(
            principal=Principal(id="u", roles=["user"]),
            resource=Resource(kind="doc", id="d", attr={"owner": "u"}),
            actions=["view"],
        )])[0]
        assert out.actions["view"].effect == "EFFECT_ALLOW"
        pdp.close()

    def test_serve(self, policy_dir):
        import urllib.request

        from cerbos_tpu.serve import serve

        pdp = serve(overrides=[
            f"storage.disk.directory={policy_dir}",
            "server.httpListenAddr=127.0.0.1:0",
            "server.grpcListenAddr=127.0.0.1:0",
            "engine.tpu.enabled=false",
        ])
        try:
            with urllib.request.urlopen(f"http://{pdp.http_addr}/_cerbos/health") as resp:
                assert json.loads(resp.read())["status"] == "SERVING"
        finally:
            pdp.close()


class TestAwsLambda:
    def test_check_via_lambda_event(self, policy_dir, tmp_path_factory, monkeypatch):
        import yaml as _yaml

        from cerbos_tpu import awslambda

        # separate dir: policy_dir recursively scans its own tmp_path
        cfg = tmp_path_factory.mktemp("lambda-cfg") / "cfg.yaml"
        cfg.write_text(_yaml.safe_dump({
            "storage": {"driver": "disk", "disk": {"directory": str(policy_dir)}},
            "engine": {"tpu": {"enabled": False}},
        }))
        monkeypatch.setenv("CERBOS_CONFIG", str(cfg))
        awslambda.reset()
        try:
            event = {
                "rawPath": "/api/check/resources",
                "requestContext": {"http": {"method": "POST"}},
                "body": json.dumps({
                    "requestId": "l1",
                    "principal": {"id": "u", "roles": ["user"]},
                    "resources": [{"actions": ["view"],
                                   "resource": {"kind": "doc", "id": "d", "attr": {"owner": "u"}}}],
                }),
            }
            resp = awslambda.lambda_handler(event)
            assert resp["statusCode"] == 200
            body = json.loads(resp["body"])
            assert body["results"][0]["actions"]["view"] == "EFFECT_ALLOW"

            health = awslambda.lambda_handler({"rawPath": "/_cerbos/health"})
            assert json.loads(health["body"]) == {"status": "SERVING"}

            bad = awslambda.lambda_handler({
                "rawPath": "/api/check/resources",
                "requestContext": {"http": {"method": "POST"}},
                "body": "{broken",
            })
            assert bad["statusCode"] == 400
        finally:
            awslambda.reset()


class TestOTLPExporter:
    def test_spans_flush_to_collector(self):
        import http.server
        import threading as th

        from cerbos_tpu import observability as obs

        received = []

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path, json.loads(self.rfile.read(n))))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Sink)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            exp = obs.OTLPSpanExporter(
                f"http://127.0.0.1:{srv.server_port}", service_name="t", flush_interval_s=60
            )
            old = obs._exporter
            obs.set_exporter(exp)
            try:
                with obs.start_span("engine.Check", batch=3):
                    with obs.start_span("ruletable.Check"):
                        pass
            finally:
                obs.set_exporter(old)
            exp.close()
            assert received, "no OTLP batch received"
            path, body = received[0]
            assert path == "/v1/traces"
            spans = body["resourceSpans"][0]["scopeSpans"][0]["spans"]
            names = {s["name"] for s in spans}
            assert names == {"engine.Check", "ruletable.Check"}
            child = next(s for s in spans if s["name"] == "ruletable.Check")
            parent = next(s for s in spans if s["name"] == "engine.Check")
            assert child["parentSpanId"] == parent["spanId"]
            assert child["traceId"] == parent["traceId"]
        finally:
            srv.shutdown()


class TestPlanAudit:
    def test_write_plan_entry_shape(self, policy_dir):
        """Plan decision entries carry DecisionLogEntry.PlanResources input/
        output plus auditTrail.effectivePolicies for queried bindings
        (audit.proto; plan.go effectivePolicies)."""
        from cerbos_tpu.audit.log import AuditLog
        from cerbos_tpu.plan import Planner
        from cerbos_tpu.plan.types import PlanInput
        from cerbos_tpu.ruletable import build_rule_table

        entries = []

        class Capture:
            def write(self, entry):
                entries.append(entry)

        table = build_rule_table(compile_policy_set(DiskStore(str(policy_dir)).get_all()))
        planner = Planner(table)
        out = planner.plan(
            PlanInput(
                request_id="pr1",
                actions=["view"],
                principal=Principal(id="alice", roles=["user"]),
                resource_kind="doc",
            )
        )
        assert "resource.doc.vdefault" in out.effective_policies

        log = AuditLog(backend=Capture(), decision_logs_enabled=True)
        log.write_plan("call-1", PlanInput(
            request_id="pr1",
            actions=["view"],
            principal=Principal(id="alice", roles=["user"]),
            resource_kind="doc",
        ), out)
        log.close()
        assert len(entries) == 1
        e = entries[0]
        pr = e["planResources"]
        assert pr["input"]["principal"]["id"] == "alice"
        assert pr["input"]["resource"]["kind"] == "doc"
        assert pr["output"]["filter"]["kind"] == "KIND_CONDITIONAL"
        assert "condition" in pr["output"]["filter"]  # machine-readable operand tree
        assert "filterDebug" in pr["output"]
        ep = e["auditTrail"]["effectivePolicies"]
        assert "resource.doc.vdefault" in ep
        # SourceAttributes wrapping matches the check path (audit.proto)
        assert "attributes" in ep["resource.doc.vdefault"]


class TestBundleCodec:
    def test_malformed_untrusted_bundles_degrade_to_codec_error(self):
        """Any structural malformation must raise CodecError (the BundleStore
        fallback trigger), never an arbitrary exception."""
        import json as _json

        import pytest as _pytest

        from cerbos_tpu.bundle_codec import CodecError, decode_compiled

        evil = [
            b"not json",
            b"[]",
            _json.dumps({"v": 999}).encode(),
            _json.dumps({"v": 1, "nodes": [], "policies": [{"k": "R"}]}).encode(),  # missing fields
            _json.dumps({"v": 1, "nodes": [], "policies": [{
                "k": "R", "fqn": "f", "res": "r", "raw": "r", "ver": "v",
                "sc": "", "sp": "", "par": 0, "rules": [], "dr": [],
            }]}).encode(),  # params ref into empty node table
            _json.dumps({"v": 1, "nodes": [["P", {"$M": []}, [0]]], "policies": [{
                "k": "R", "fqn": "f", "res": "r", "raw": "r", "ver": "v",
                "sc": "", "sp": "", "par": 0, "rules": [], "dr": [],
            }]}).encode(),  # self-referential params: recursion must not escape
            _json.dumps({"v": 1, "nodes": [["wat", 1]], "policies": [{
                "k": "R", "fqn": "f", "res": "r", "raw": "r", "ver": "v",
                "sc": "", "sp": "", "par": 0, "rules": [], "dr": [],
            }]}).encode(),  # unknown node tag, referenced
            _json.dumps({"v": 1, "nodes": [], "policies": [{"k": "Z"}]}).encode(),
        ]
        for blob in evil:
            with _pytest.raises(CodecError):
                decode_compiled(blob)
