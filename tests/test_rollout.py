"""Safe policy rollout drills (docs/ROBUSTNESS.md, "Safe policy rollout").

Proves the acceptance criteria of the rollout tentpole: every swap is a
staged build → lower → gate → cutover → canary ladder; cutovers are
epoch-versioned and barrier-atomic (zero lost requests, zero mixed-epoch
decisions under continuous traffic); a gate-rejected bundle never serves a
request; a poisoned bundle is auto-rolled back by the canary; the committed
epoch propagates over the ticket queue to front ends within bounded skew;
and the `swap_fail:STAGE` knob injects failures at exactly one stage.
"""

import os
import threading
import time

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import rollout as rollout_mod
from cerbos_tpu.engine import types as T
from cerbos_tpu.engine.batcher import BatchingEvaluator
from cerbos_tpu.engine.faults import parse_fault_spec
from cerbos_tpu.engine.rollout import (
    EPOCH_ATTR,
    OUTCOME_FAILED,
    OUTCOME_REJECTED,
    OUTCOME_ROLLED_BACK,
    OUTCOME_SERVING,
    RolloutController,
    SwapBarrier,
    bundle_hash_of,
    epoch_of,
)
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

pytestmark = pytest.mark.rollout

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""

# the same policy with the user rule flipped to a deny: a legitimate (if
# dramatic) policy change whose behavior diff the gate's replay must surface
POLICY_V2 = POLICY.replace("effect: EFFECT_ALLOW\n      roles: [user]", "effect: EFFECT_DENY\n      roles: [user]")

# runtime.effectiveDerivedRoles membership is oracle-only by construction
# (tests/test_analyze.py) — the bundle `failOn: oracle-only` must reject
ORACLE_ONLY_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: '"admin" in runtime.effectiveDerivedRoles'
"""


def table(src: str = POLICY):
    return build_rule_table(compile_policy_set(list(parse_policies(src))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i}", "public": False, **attr},
        ),
        actions=["view"],
    )


def wait_for(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class OracleEvaluator:
    """Minimal evaluator backed by the CPU oracle (as in test_chaos)."""

    def __init__(self, rt):
        self.rule_table = rt
        self.schema_mgr = None
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return [check_input(self.rule_table, i, params or EvalParams()) for i in inputs]

    def submit(self, inputs, params=None):
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


class FakeManager:
    """RuleTableManager stand-in: `policy_text` is "the store"; build_table
    compiles it fresh and commit_table publishes, like the real thing."""

    def __init__(self, policy_text: str = POLICY):
        self.policy_text = policy_text
        self.rule_table = table(policy_text)
        self.commits: list = []

    def build_table(self):
        return table(self.policy_text)

    def commit_table(self, rt):
        self.rule_table = rt
        self.commits.append(rt)


class FakeSentinel:
    """The slice of ParitySentinel the controller reads: the stats dict the
    canary baselines, the recent-input ring the gate replays, set_boost."""

    def __init__(self, inputs=None):
        self.stats = {"divergences": 0, "storms": 0, "checks": 0}
        self._recent = list(inputs or [])
        self.boosts: list = []

    def recent_inputs(self):
        return list(self._recent)

    def set_boost(self, rate, duration_s):
        self.boosts.append((rate, duration_s))


def make_ctl(manager=None, sentinel=None, lanes=None, **conf):
    # the canary consults the process-global pressure monitor, which other
    # suites (brownout, overload) saturate; keep module tests hermetic by
    # defaulting the pressure trigger out of reach
    conf.setdefault("rollbackAt", 9.9)
    ctl = RolloutController(
        manager if manager is not None else FakeManager(),
        conf=conf,
        sentinel=sentinel,
    )
    if lanes is not None:
        ctl.bind_lanes(lanes)
    ctl.seed(ctl.manager.rule_table)
    return ctl


class TestFaultSpec:
    def test_swap_fail_grammar(self):
        assert parse_fault_spec("swap_fail:gate") == {"swap_fail": "gate"}
        assert parse_fault_spec("swap_fail:build,shard:1") == {"swap_fail": "build", "shard": 1}

    @pytest.mark.parametrize("stage", ["build", "lower", "gate", "canary"])
    def test_all_stages_accepted(self, stage):
        assert parse_fault_spec(f"swap_fail:{stage}")["swap_fail"] == stage

    def test_unknown_stage_rejected(self):
        with pytest.raises(ValueError):
            parse_fault_spec("swap_fail:bogus")


class TestSwapBarrier:
    def test_no_lanes_is_trivially_parked(self):
        b = SwapBarrier(timeout_s=0.2)
        assert b.start([]) is True
        assert not b.timed_out
        b.release()

    def test_parks_and_releases_live_lanes(self):
        b = SwapBarrier(timeout_s=5.0)
        parked_at = []
        released_at = []

        class Lane:
            def request_swap(self, barrier):
                def drain():
                    parked_at.append(time.monotonic())
                    barrier.park(self)
                    released_at.append(time.monotonic())

                threading.Thread(target=drain, daemon=True).start()
                return True

        lanes = [Lane(), Lane()]
        assert b.start(lanes) is True
        assert b.expected == 2
        assert len(parked_at) == 2
        assert not released_at  # stopped world: lanes hold until release
        b.release()
        assert wait_for(lambda: len(released_at) == 2)

    def test_wedged_lane_cannot_hold_cutover_hostage(self):
        b = SwapBarrier(timeout_s=0.2)

        class WedgedLane:
            def request_swap(self, barrier):
                return True  # accepts, never parks

        t0 = time.monotonic()
        assert b.start([WedgedLane()]) is False
        assert b.timed_out
        assert time.monotonic() - t0 < 2.0
        b.release()

    def test_dead_lane_is_not_counted(self):
        b = SwapBarrier(timeout_s=0.5)

        class DeadLane:
            def request_swap(self, barrier):
                return False

        assert b.start([DeadLane()]) is True
        assert b.expected == 0


class TestEpochIdentity:
    def test_seed_stamps_epoch_one(self):
        ctl = make_ctl()
        assert ctl.epoch.number == 1
        assert ctl.epoch.source == "boot"
        assert epoch_of(ctl.manager.rule_table) == 1

    def test_bundle_hash_is_content_stable(self):
        assert bundle_hash_of(table()) == bundle_hash_of(table())
        assert bundle_hash_of(table()) != bundle_hash_of(table(POLICY_V2))
        assert len(bundle_hash_of(table())) == 16

    def test_never_committed_table_has_no_epoch(self):
        assert epoch_of(table()) is None


class TestStagedRollout:
    def test_good_swap_walks_the_ladder(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr)
        seen = []
        ctl.subscribe("probe", lambda ep: seen.append(ep))
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_SERVING
        assert (run.from_epoch, run.to_epoch) == (1, 2)
        by_stage = {s["stage"]: s["status"] for s in run.stages}
        assert by_stage == {
            "build": "ok",
            "lower": "ok",
            "gate": "ok",
            "cutover": "ok",
            "canary": "skipped",
        }
        assert ctl.epoch.number == 2
        assert epoch_of(mgr.rule_table) == 2
        assert mgr.commits and mgr.commits[-1] is ctl.epoch.rule_table
        assert [ep.number for ep in seen] == [2]
        assert run.bundle_hash == bundle_hash_of(mgr.rule_table)
        # the displaced epoch stays resident for rollback
        assert [e.number for e in ctl.history] == [1]

    def test_gate_rejects_oracle_only_bundle(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr, failOn="oracle-only")
        old_table = mgr.rule_table
        mgr.policy_text = ORACLE_ONLY_POLICY
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_REJECTED
        assert run.error == "analyzer:oracle-only"
        # the rejected bundle never became the serving table
        assert mgr.rule_table is old_table
        assert not mgr.commits
        assert ctl.epoch.number == 1
        gate = run.to_dict()["gate"]
        assert gate["fail_on"] == "oracle-only"
        assert gate["findings"], "rejection must carry reason-coded findings"
        assert all({"code", "severity", "message"} <= set(f) for f in gate["findings"])
        # live analysis objects never leak into the serialized report
        assert "_analysis_report" not in gate

    def test_replay_surfaces_behavior_diffs(self):
        owner_view = inp(3)  # owner matches -> ALLOW under v1, DENY under v2
        mgr = FakeManager()
        ctl = make_ctl(mgr, sentinel=FakeSentinel([owner_view]))
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_SERVING  # a diff is news, not an error
        replay = run.gate["replay"]
        assert replay["replayed"] == 1
        assert replay["diffs"] == 1
        assert replay["samples"][0]["principal"] == "u3"

    def test_require_ack_turns_diffs_into_rejection(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr, sentinel=FakeSentinel([inp(3)]), requireAck=True)
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_REJECTED
        assert run.error == "diffs_require_ack:1"
        assert ctl.epoch.number == 1
        assert not mgr.commits

    @pytest.mark.parametrize("stage", ["build", "lower", "gate"])
    def test_swap_fail_knob_fails_exactly_that_stage(self, stage):
        mgr = FakeManager()
        ctl = RolloutController(mgr, conf={}, faults=parse_fault_spec(f"swap_fail:{stage}"))
        ctl.seed(mgr.rule_table)
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_FAILED
        assert f"swap_fail:{stage}" in run.error
        failed = [s for s in run.stages if s["status"] == "failed"]
        assert [s["stage"] for s in failed] == [stage]
        assert ctl.epoch.number == 1  # last valid epoch kept serving
        assert not mgr.commits

    def test_operator_rollback_and_epoch_numbers_never_reused(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr)
        mgr.policy_text = POLICY_V2
        assert ctl.run_rollout(trigger="test").to_epoch == 2
        report = ctl.rollback(reason="operator")
        assert report["outcome"] == OUTCOME_ROLLED_BACK
        assert ctl.epoch.number == 1
        assert ctl.epoch.source == "rollback"
        assert epoch_of(mgr.rule_table) == 1
        # the next rollout takes the next UNUSED number — 2 is burned
        mgr.policy_text = POLICY
        assert ctl.run_rollout(trigger="test").to_epoch == 3

    def test_rollback_without_resident_history_is_refused(self):
        ctl = make_ctl()
        assert ctl.rollback(reason="operator") is None
        assert ctl.epoch.number == 1

    def test_failing_subscriber_never_tears_the_commit(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr)
        after = []
        ctl.subscribe("bad", lambda ep: (_ for _ in ()).throw(RuntimeError("boom")))
        ctl.subscribe("good", lambda ep: after.append(ep.number))
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_SERVING
        assert after == [2]  # later subscribers still ran

    def test_wait_report_blocks_until_terminal(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr)
        gen = ctl.generation
        mgr.policy_text = POLICY_V2
        done = []
        t = threading.Thread(target=lambda: done.append(ctl.wait_report(gen, timeout=10.0)))
        t.start()
        ctl.run_rollout(trigger="test")
        t.join(timeout=10.0)
        assert done and done[0]["outcome"] == OUTCOME_SERVING
        assert done[0]["to_epoch"] == 2
        # nothing newer than the latest generation: bounded timeout, None
        assert ctl.wait_report(ctl.generation, timeout=0.1) is None

    def test_snapshot_shape(self):
        ctl = make_ctl(lanes=[])
        snap = ctl.snapshot()
        assert snap["mode"] == "full"
        assert snap["epoch"]["epoch"] == 1
        assert set(snap) == {"mode", "epoch", "history", "lanes", "runs", "config"}
        assert snap["config"]["enabled"] is True

    def test_disabled_controller_swaps_without_gate(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr, enabled=False)
        mgr.policy_text = POLICY_V2
        run = ctl.run_rollout(trigger="test")
        assert run.outcome == OUTCOME_SERVING
        by_stage = {s["stage"]: s["status"] for s in run.stages}
        assert by_stage["lower"] == "skipped"
        assert by_stage["gate"] == "skipped"
        assert ctl.epoch.number == 2  # still epoch-versioned and atomic


class TestCanary:
    def test_fresh_divergence_triggers_auto_rollback(self):
        mgr = FakeManager()
        sent = FakeSentinel()
        ctl = make_ctl(mgr, sentinel=sent, canarySec=30, canaryPollMs=10, canaryBoost=4.0)
        try:
            mgr.policy_text = POLICY_V2
            run = ctl.run_rollout(trigger="test")
            assert ctl.epoch.number == 2  # cutover done, canary holding
            assert not run.terminal
            assert sent.boosts == [(4.0, 30.0)]
            sent.stats["divergences"] += 1
            assert run.wait(10.0)
            assert run.outcome == OUTCOME_ROLLED_BACK
            assert run.canary["trigger"] == "parity_divergence:1"
            assert ctl.epoch.number == 1
            assert ctl.epoch.source == "rollback"
            assert epoch_of(mgr.rule_table) == 1
        finally:
            ctl.close()

    def test_quiet_canary_passes(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr, sentinel=FakeSentinel(), canarySec=0.2, canaryPollMs=10)
        try:
            mgr.policy_text = POLICY_V2
            run = ctl.run_rollout(trigger="test")
            assert run.wait(10.0)
            assert run.outcome == OUTCOME_SERVING
            assert run.canary["result"] == "pass"
            assert ctl.epoch.number == 2
        finally:
            ctl.close()

    def test_swap_fail_canary_knob_drills_the_rollback_path(self):
        mgr = FakeManager()
        ctl = RolloutController(
            mgr,
            conf={"canarySec": 30, "canaryPollMs": 10},
            faults=parse_fault_spec("swap_fail:canary"),
        )
        ctl.seed(mgr.rule_table)
        try:
            mgr.policy_text = POLICY_V2
            run = ctl.run_rollout(trigger="test")
            assert run.wait(10.0)
            assert run.outcome == OUTCOME_ROLLED_BACK
            assert run.canary["trigger"] == "fault:swap_fail:canary"
            assert ctl.epoch.number == 1
        finally:
            ctl.close()

    def test_new_rollout_supersedes_the_canary_hold(self):
        mgr = FakeManager()
        ctl = make_ctl(mgr, sentinel=FakeSentinel(), canarySec=30, canaryPollMs=10)
        try:
            mgr.policy_text = POLICY_V2
            first = ctl.run_rollout(trigger="test")
            assert not first.terminal
            mgr.policy_text = POLICY
            second = ctl.run_rollout(trigger="test")
            assert first.wait(10.0)
            assert first.outcome == OUTCOME_SERVING
            assert first.canary["result"] == "superseded"
            assert second.to_epoch == 3
        finally:
            ctl.close()


class TestAtomicCutoverUnderTraffic:
    def test_zero_lost_zero_mixed_epoch_with_live_lane(self):
        """Continuous traffic through a real batcher lane across repeated
        cutovers: every request is answered, every decision carries exactly
        one epoch, and the effect each decision reports is the one its
        epoch's table produces — no request spans two tables."""
        mgr = FakeManager()
        ev = OracleEvaluator(mgr.rule_table)
        lane = BatchingEvaluator(ev, max_wait_ms=1.0)
        ctl = make_ctl(mgr, lanes=[lane])
        ctl.subscribe("evaluator", lambda ep: setattr(ev, "rule_table", ep.rule_table))
        stop = threading.Event()
        decisions: list[tuple] = []
        errors: list = []

        def traffic():
            k = 0
            while not stop.is_set():
                k += 1
                try:
                    out = lane.check([inp(3)])  # owner view: v1 ALLOW / v2 DENY
                    decisions.append((T.current_epoch(), out[0].actions["view"].effect))
                except Exception as e:  # noqa: BLE001 — a lost request fails the drill
                    errors.append(e)

        threads = [threading.Thread(target=traffic, daemon=True) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            wait_for(lambda: len(decisions) > 20)
            for text in (POLICY_V2, POLICY, POLICY_V2):
                mgr.policy_text = text
                run = ctl.run_rollout(trigger="test")
                assert run.outcome == OUTCOME_SERVING
                wait_for(lambda n=len(decisions): len(decisions) > n + 20)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
            lane.close()
            ctl.close()

        assert not errors, errors[:3]
        assert all(ep is not None for ep, _ in decisions)
        # atomicity: one epoch -> exactly one behavior, and it is the
        # behavior that epoch's policy text defines
        effect_by_epoch = {}
        for ep, effect in decisions:
            effect_by_epoch.setdefault(ep, set()).add(effect)
        assert all(len(v) == 1 for v in effect_by_epoch.values()), effect_by_epoch
        expected = {1: "EFFECT_ALLOW", 2: "EFFECT_DENY", 3: "EFFECT_ALLOW", 4: "EFFECT_DENY"}
        for ep, effects in effect_by_epoch.items():
            assert effects == {expected[ep]}, (ep, effects)
        assert set(effect_by_epoch) >= {1, 4}  # saw first and last epoch
        assert lane.epoch == 4

    def test_sharded_pool_cuts_over_all_lanes(self):
        from cerbos_tpu.engine.shards import build_shard_pool
        from cerbos_tpu.tpu.evaluator import TpuEvaluator

        mgr = FakeManager()
        base = TpuEvaluator(mgr.rule_table, use_jax=False, min_device_batch=1)
        pool = build_shard_pool(
            base, n_shards=2, routing="round_robin", max_wait_ms=0.0, request_timeout_s=10.0
        )
        ctl = make_ctl(mgr, lanes=pool.swap_lanes())

        def swap_evaluator(ep):
            base.rule_table = ep.rule_table
            base.lowered.table = ep.rule_table
            base.refresh()

        ctl.subscribe("evaluator", swap_evaluator)
        ctl.subscribe("shards", lambda ep: pool.refresh_shards(ep.rule_table))
        try:
            before = [pool.check([inp(3)])[0].actions["view"].effect for _ in range(4)]
            assert set(before) == {"EFFECT_ALLOW"}
            mgr.policy_text = POLICY_V2
            run = ctl.run_rollout(trigger="test")
            assert run.outcome == OUTCOME_SERVING
            # both lanes stamped — round-robin hits each shard
            assert [lane.epoch for lane in pool.swap_lanes()] == [2, 2]
            after = [pool.check([inp(3)])[0].actions["view"].effect for _ in range(4)]
            assert set(after) == {"EFFECT_DENY"}
        finally:
            ctl.close()
            pool.close()


class TestIpcEpochPropagation:
    def test_two_frontends_converge_within_bounded_skew(self, tmp_path):
        """`--frontends 2 --shards 2` shape, in-process: the committed epoch
        rides the STATUS frames from a sharded pool's process; both front
        ends observe the cutover within a couple of status-poll intervals,
        and their decisions stamp the batcher's epoch."""
        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient
        from cerbos_tpu.engine.shards import build_shard_pool
        from cerbos_tpu.tpu.evaluator import TpuEvaluator

        mgr = FakeManager()
        base = TpuEvaluator(mgr.rule_table, use_jax=False, min_device_batch=1)
        pool = build_shard_pool(
            base, n_shards=2, routing="round_robin", max_wait_ms=1.0, request_timeout_s=10.0
        )
        ctl = make_ctl(mgr, lanes=pool.swap_lanes())

        def swap_evaluator(ep):
            base.rule_table = ep.rule_table
            base.lowered.table = ep.rule_table
            base.refresh()

        ctl.subscribe("evaluator", swap_evaluator)
        ctl.subscribe("shards", lambda ep: pool.refresh_shards(ep.rule_table))
        poll_s = 0.05
        server = BatcherIpcServer(
            str(tmp_path / "batcher.sock"),
            pool,
            readiness=lambda: {"status": "ready", **ctl.epoch_info()},
        )
        server.start()
        clients = [
            RemoteBatcherClient(
                server.socket_path,
                mgr.rule_table,
                request_timeout_s=10.0,
                worker_label=f"fe{i}",
                status_poll_s=poll_s,
                connect_retry_s=0.05,
            )
            for i in range(2)
        ]
        ctl.subscribe("clients", lambda ep: [c.refresh_table(ep.rule_table) for c in clients])

        def client_epoch(c):
            last = c._last_status or {}
            return last.get("policy_epoch")

        try:
            assert wait_for(lambda: all(client_epoch(c) == 1 for c in clients))
            mgr.policy_text = POLICY_V2
            run = ctl.run_rollout(trigger="test")
            assert run.outcome == OUTCOME_SERVING
            t0 = time.monotonic()
            assert wait_for(lambda: all(client_epoch(c) == 2 for c in clients), timeout=5.0)
            skew = time.monotonic() - t0
            assert skew < poll_s * 20 + 1.0, f"unbounded cutover skew: {skew:.3f}s"
            assert [lane.epoch for lane in pool.swap_lanes()] == [2, 2]
            for c in clients:
                out = c.check([inp(3)])
                assert out[0].actions["view"].effect == "EFFECT_DENY"
                assert T.current_epoch() == 2
        finally:
            for c in clients:
                c.close()
            server.close()
            pool.close()
            ctl.close()


class TestBootstrapIntegration:
    def _boot(self, tmp_path, policy=POLICY, overrides=()):
        from cerbos_tpu.bootstrap import initialize
        from cerbos_tpu.config import Config

        (tmp_path / "album.yaml").write_text(policy)
        config = Config.load(overrides=[f"storage.disk.directory={tmp_path}", *overrides])
        return initialize(config)

    def _rewrite(self, tmp_path, core, policy):
        path = tmp_path / "album.yaml"
        path.write_text(policy)
        # defeat mtime granularity so the disk store's change scan sees it
        bump = time.time() + 5
        os.utime(path, (bump, bump))
        core.store.check_for_changes()

    def test_storage_event_runs_a_staged_rollout(self, tmp_path):
        core = self._boot(tmp_path)
        try:
            ctl = core.rollout
            assert ctl is not None and ctl.mode == "full"
            assert ctl.epoch.number == 1
            assert "engine" in ctl.subscribers
            out = core.engine.check([inp(3)])
            assert out[0].actions["view"].effect == "EFFECT_ALLOW"
            assert T.current_epoch() == 1

            self._rewrite(tmp_path, core, POLICY_V2)
            assert ctl.epoch.number == 2
            run = ctl.runs[-1]
            assert run.outcome == OUTCOME_SERVING
            assert run.trigger == "storage"
            out = core.engine.check([inp(3)])
            assert out[0].actions["view"].effect == "EFFECT_DENY"
            assert T.current_epoch() == 2
            info = ctl.epoch_info()
            assert info["policy_epoch"] == 2
            assert info["policy_epoch_committed_at"] > 0
        finally:
            core.close()

    def test_gate_rejected_bundle_never_serves_a_request(self, tmp_path):
        core = self._boot(tmp_path, overrides=["engine.tpu.rollout.failOn=oracle-only"])
        try:
            ctl = core.rollout
            gen = ctl.generation
            self._rewrite(tmp_path, core, ORACLE_ONLY_POLICY)
            report = ctl.wait_report(gen, timeout=30.0)
            assert report is not None
            assert report["outcome"] == OUTCOME_REJECTED
            assert report["error"] == "analyzer:oracle-only"
            assert report["gate"]["findings"]
            # still serving epoch 1 with epoch-1 behavior
            assert ctl.epoch.number == 1
            out = core.engine.check([inp(3)])
            assert out[0].actions["view"].effect == "EFFECT_ALLOW"
            assert T.current_epoch() == 1
        finally:
            core.close()

    def test_poisoned_device_path_rolls_back_in_canary(self, tmp_path, monkeypatch):
        """The acceptance drill: the device path flips effects silently
        (flip_effect:1.0); the gate's CPU-side replay cannot see it, the
        cutover happens, and the canary's boosted sentinel sampling catches
        the divergence and rolls back — zero lost requests."""
        monkeypatch.setenv("CERBOS_TPU_FAULTS", "flip_effect:1.0")
        core = self._boot(
            tmp_path,
            overrides=[
                "engine.tpu.rollout.canarySec=20",
                "engine.tpu.rollout.canaryPollMs=20",
                "engine.tpu.rollout.canaryBoost=100",
                "engine.tpu.paritySentinel.sampleRate=1.0",
                "engine.tpu.paritySentinel.stormThreshold=1000",
            ],
        )
        try:
            ctl = core.rollout
            batcher = core.engine.tpu_evaluator
            self._rewrite(tmp_path, core, POLICY_V2)
            run = ctl.runs[-1]
            assert run.to_epoch == 2
            answered = 0
            deadline = time.monotonic() + 30.0
            while not run.terminal and time.monotonic() < deadline:
                answered += len(batcher.check([inp(answered)]))
                time.sleep(0.01)
            assert run.terminal, "canary never resolved"
            assert run.outcome == OUTCOME_ROLLED_BACK
            assert run.canary["trigger"].startswith("parity_")
            assert answered > 0  # traffic flowed throughout; none lost
            assert ctl.epoch.number == 1
            assert ctl.epoch.source == "rollback"
        finally:
            core.close()


class TestCtlReportRendering:
    def test_print_rollout_report_renders_stages_and_findings(self, capsys):
        from cerbos_tpu.ctl import _print_rollout_report

        _print_rollout_report(
            {
                "generation": 3,
                "trigger": "storage",
                "outcome": OUTCOME_REJECTED,
                "from_epoch": 1,
                "to_epoch": None,
                "bundle_hash": "abcd1234",
                "stages": [
                    {"stage": "build", "status": "ok", "seconds": 0.5},
                    {"stage": "gate", "status": "rejected", "seconds": 0.1, "reason": "analyzer:oracle-only"},
                ],
                "gate": {
                    "analysis": {"classes": {"oracle-only": 1}},
                    "findings": [
                        {
                            "severity": "error",
                            "code": "operand_unsupported",
                            "policy": "album",
                            "rule": "r1",
                            "message": "oracle-only condition",
                        }
                    ],
                    "replay": {"replayed": 4, "diffs": 1, "errors": 0, "samples": []},
                },
                "canary": {},
                "error": "analyzer:oracle-only",
            }
        )
        out = capsys.readouterr().out
        assert "build" in out and "gate" in out
        assert "rejected" in out
        assert "operand_unsupported" in out
        assert "outcome: rejected" in out

    def test_module_handle_mirrors_bootstrap(self):
        ctl = make_ctl()
        rollout_mod.install(ctl)
        try:
            assert rollout_mod.active() is ctl
        finally:
            rollout_mod.install(None)


class TestDiskStoreReload:
    """Operator `store reload` must rescan the directory before notifying:
    the base EVENT_RELOAD contract rebuilds from the store's cached
    snapshot, so an admin-triggered rollout would gate and serve the STALE
    bundle (the on-disk edit only landing at the next watch poll — or never
    with watching disabled)."""

    def _store(self, tmp_path):
        from cerbos_tpu.storage.disk import DiskStore

        (tmp_path / "album.yaml").write_text(POLICY)
        return DiskStore(str(tmp_path), watch_for_changes=False)

    def test_reload_picks_up_disk_edits_without_a_watcher(self, tmp_path):
        store = self._store(tmp_path)
        events: list = []
        store.subscribe(lambda evs: events.extend(evs))
        old_hash = bundle_hash_of(build_rule_table(compile_policy_set(store.get_all())))

        path = tmp_path / "album.yaml"
        path.write_text(POLICY_V2)
        os.utime(path, (time.time() + 5, time.time() + 5))
        store.reload()

        assert events and events[0].kind == "ADD_OR_UPDATE"
        new_hash = bundle_hash_of(build_rule_table(compile_policy_set(store.get_all())))
        assert new_hash != old_hash  # subscribers rebuild what is on disk NOW

    def test_unchanged_reload_still_fires_the_full_rebuild_signal(self, tmp_path):
        store = self._store(tmp_path)
        events: list = []
        store.subscribe(lambda evs: events.extend(evs))
        store.reload()
        # `reload --wait` needs a rollout run to report on even when the
        # directory is unchanged
        assert [e.kind for e in events] == ["RELOAD"]
