"""Reference verify corpus: policy-test framework gated on TestResults goldens.

Mirrors internal/verify/verify_test.go TestVerify: each case_NNN.yaml is a
VerifyTestCase descriptor (description, config), the .input is a txtar
archive of a test-suite directory, and the .golden is the protojson
TestResults produced by running the suites against the golden policy store
engine. Comparison normalizes numbers and sorts repeated suites by file,
exactly as the reference's protocmp options do.
"""

import json
import os
import re

import pytest
import yaml

from cerbos_tpu.verify.results import Config, verify
from golden_loader import golden_engine

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "verify", "cases")

CASES = sorted(
    f for f in os.listdir(CORPUS)
    if f.endswith(".yaml") and os.path.exists(os.path.join(CORPUS, f + ".golden"))
)


def expand_txtar(data: str, dest: str) -> None:
    """Minimal txtar: `-- name --` headers, body until the next header."""
    current = None
    lines: list[str] = []

    def flush():
        if current is None:
            return
        path = os.path.join(dest, current)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))

    for line in data.splitlines():
        m = re.match(r"^-- (.+?) --$", line)
        if m:
            flush()
            current = m.group(1).strip()
            lines = []
        elif current is not None:
            lines.append(line)
    flush()


def _norm(v):
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, str):
        return v.replace(" ", " ")  # the reference's NBSP comparer
    return v


def _conf_from(case: dict) -> Config:
    cfg = case.get("config") or {}
    return Config(
        excluded_resource_policy_fqns=set(cfg.get("excludedResourcePolicyFqns", []) or []),
        excluded_principal_policy_fqns=set(cfg.get("excludedPrincipalPolicyFqns", []) or []),
        included_test_names_regexp=cfg.get("includedTestNamesRegexp", "") or "",
    )


@pytest.fixture(scope="module")
def engine():
    return golden_engine()


@pytest.mark.parametrize("case", CASES)
def test_verify_case(case, engine, tmp_path):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        descriptor = yaml.safe_load(f) or {}
    with open(os.path.join(CORPUS, case + ".input"), encoding="utf-8") as f:
        expand_txtar(f.read(), str(tmp_path))
    with open(os.path.join(CORPUS, case + ".golden"), encoding="utf-8") as f:
        want = json.load(f)

    have = verify(str(tmp_path), engine, _conf_from(descriptor))

    want["suites"] = sorted(want.get("suites", []), key=lambda s: s.get("file", ""))
    have["suites"] = sorted(have.get("suites", []), key=lambda s: s.get("file", ""))
    assert _norm(want) == _norm(have), case
