"""Golden rendering checks for deploy/charts/cerbos-tpu.

``helm template`` is driven over three values variants (defaults, TLS,
policies-from-ConfigMap + engine overrides) and the rendered manifests are
asserted structurally. Skips cleanly when helm is not installed; the static
chart checks at the bottom run regardless.
"""

import os
import shutil
import subprocess

import pytest
import yaml

CHART_DIR = os.path.join(
    os.path.dirname(__file__), "..", "deploy", "charts", "cerbos-tpu"
)

HELM = shutil.which("helm")


def render(*set_args):
    cmd = [HELM, "template", "pdp", CHART_DIR]
    for s in set_args:
        cmd += ["--set", s]
    out = subprocess.run(cmd, capture_output=True, text=True, check=True).stdout
    docs = [d for d in yaml.safe_load_all(out) if d]
    return {(d["kind"], d["metadata"]["name"]): d for d in docs}


def container(deployment):
    return deployment["spec"]["template"]["spec"]["containers"][0]


@pytest.mark.skipif(HELM is None, reason="helm not installed")
class TestHelmTemplate:
    def test_default_values(self):
        docs = render()
        assert set(docs) == {
            ("Deployment", "pdp-cerbos-tpu"),
            ("Service", "pdp-cerbos-tpu"),
            ("ConfigMap", "pdp-cerbos-tpu-config"),
        }
        dep = docs[("Deployment", "pdp-cerbos-tpu")]
        c = container(dep)
        assert c["image"] == "cerbos-tpu:latest"
        assert c["args"] == ["server", "--config", "/config/config.yaml"]
        # config rollouts restart pods: the checksum annotation must exist
        ann = dep["spec"]["template"]["metadata"]["annotations"]
        assert len(ann["checksum/config"]) == 64
        # probes stay plain HTTP without TLS
        assert "scheme" not in c["livenessProbe"]["httpGet"]
        # readiness is warmup-gated and split from liveness
        assert c["livenessProbe"]["httpGet"]["path"] == "/_cerbos/health"
        assert c["readinessProbe"]["httpGet"]["path"] == "/_cerbos/ready"
        # the rendered config carries the streaming knobs end to end
        conf = yaml.safe_load(
            docs[("ConfigMap", "pdp-cerbos-tpu-config")]["data"]["config.yaml"]
        )
        tpu = conf["engine"]["tpu"]
        assert tpu["enabled"] is True
        assert tpu["streamingThreshold"] == 1024
        assert tpu["inflightDepth"] == 3
        assert tpu["pipelineChunk"] == 4096
        # device-path fault domain defaults (docs/ROBUSTNESS.md)
        assert tpu["breaker"]["enabled"] is True
        assert tpu["breaker"]["failureThreshold"] == 5
        assert tpu["quarantineMax"] == 128
        assert "tls" not in conf.get("server", {})
        svc = docs[("Service", "pdp-cerbos-tpu")]
        assert {(p["name"], p["port"]) for p in svc["spec"]["ports"]} == {
            ("http", 3592),
            ("grpc", 3593),
        }

    def test_tls_variant(self):
        docs = render("tls.secretName=pdp-tls")
        dep = docs[("Deployment", "pdp-cerbos-tpu")]
        c = container(dep)
        assert c["livenessProbe"]["httpGet"]["scheme"] == "HTTPS"
        assert c["readinessProbe"]["httpGet"]["scheme"] == "HTTPS"
        vols = {v["name"]: v for v in dep["spec"]["template"]["spec"]["volumes"]}
        assert vols["tls"]["secret"]["secretName"] == "pdp-tls"
        assert {"name": "tls", "mountPath": "/tls"} in c["volumeMounts"]
        conf = yaml.safe_load(
            docs[("ConfigMap", "pdp-cerbos-tpu-config")]["data"]["config.yaml"]
        )
        assert conf["server"]["tls"] == {"cert": "/tls/tls.crt", "key": "/tls/tls.key"}

    def test_policies_configmap_and_engine_overrides(self):
        docs = render(
            "policies.configMapName=my-policies",
            "cerbos.config.engine.tpu.inflightDepth=2",
            "cerbos.config.engine.tpu.streamingThreshold=512",
        )
        dep = docs[("Deployment", "pdp-cerbos-tpu")]
        vols = {v["name"]: v for v in dep["spec"]["template"]["spec"]["volumes"]}
        assert vols["policies"]["configMap"]["name"] == "my-policies"
        assert {"name": "policies", "mountPath": "/policies"} in container(dep)[
            "volumeMounts"
        ]
        conf = yaml.safe_load(
            docs[("ConfigMap", "pdp-cerbos-tpu-config")]["data"]["config.yaml"]
        )
        assert conf["engine"]["tpu"]["inflightDepth"] == 2
        assert conf["engine"]["tpu"]["streamingThreshold"] == 512


class TestChartStatic:
    """Checks that hold without helm installed."""

    def test_chart_metadata(self):
        with open(os.path.join(CHART_DIR, "Chart.yaml"), encoding="utf-8") as f:
            chart = yaml.safe_load(f)
        assert chart["name"] == "cerbos-tpu"
        assert chart["apiVersion"] == "v2"

    def test_default_values_parse_and_match_engine_defaults(self):
        with open(os.path.join(CHART_DIR, "values.yaml"), encoding="utf-8") as f:
            values = yaml.safe_load(f)
        tpu = values["cerbos"]["config"]["engine"]["tpu"]
        from cerbos_tpu.config import DEFAULTS

        want = DEFAULTS["engine"]["tpu"]
        for knob in ("streamingThreshold", "inflightDepth", "pipelineChunk", "quarantineMax"):
            assert tpu[knob] == want[knob], knob
        for knob in ("enabled", "failureThreshold", "probeBackoffBaseMs", "probeBackoffCapMs"):
            assert tpu["breaker"][knob] == want["breaker"][knob], knob
        for knob in ("enabled", "capacity"):
            assert tpu["flightRecorder"][knob] == want["flightRecorder"][knob], knob
        for knob in ("enabled", "batchSizes", "background", "timeoutSeconds"):
            assert tpu["warmup"][knob] == want["warmup"][knob], knob
        for knob in ("enabled", "maxArtifacts", "maxSeconds"):
            assert tpu["profiler"][knob] == want["profiler"][knob], knob
        for knob in ("enabled", "slowRingCapacity", "slowThresholdMs"):
            assert tpu["latencyBudget"][knob] == want["latencyBudget"][knob], knob
        for knob in ("enabled", "intervalMs", "windowSec"):
            assert tpu["pressure"][knob] == want["pressure"][knob], knob
        for knob in ("socketPath", "transport", "ringKiB", "requestTimeoutMs", "maxOutstanding"):
            assert tpu["sharedBatcher"][knob] == want["sharedBatcher"][knob], knob
        # overload control block (docs/ROBUSTNESS.md, "Overload & brownout")
        overload = values["cerbos"]["config"]["overload"]
        want_ov = DEFAULTS["overload"]
        assert overload["enabled"] == want_ov["enabled"]
        assert overload["classes"] == want_ov["classes"]
        for knob in ("enabled", "hysteresis", "holdSeconds", "stages"):
            assert overload["brownout"][knob] == want_ov["brownout"][knob], knob

    def test_readiness_probe_split_from_liveness(self):
        # a cold replica must not take traffic until warmup has compiled the
        # expected device layouts; liveness stays on the plain health endpoint
        with open(
            os.path.join(CHART_DIR, "templates", "deployment.yaml"), encoding="utf-8"
        ) as f:
            tpl = f.read()
        assert "/_cerbos/ready" in tpl
        assert "/_cerbos/health" in tpl

    def test_prometheus_scrape_annotations(self):
        with open(os.path.join(CHART_DIR, "values.yaml"), encoding="utf-8") as f:
            values = yaml.safe_load(f)
        assert values["metrics"] == {"scrape": True, "path": "/_cerbos/metrics"}
        with open(
            os.path.join(CHART_DIR, "templates", "deployment.yaml"), encoding="utf-8"
        ) as f:
            tpl = f.read()
        for ann in ("prometheus.io/scrape", "prometheus.io/path", "prometheus.io/port"):
            assert ann in tpl, ann

    def test_grafana_dashboard_parses_and_targets_registry_metrics(self):
        import json
        import re

        path = os.path.join(os.path.dirname(CHART_DIR), "..", "grafana-dashboard.json")
        with open(path, encoding="utf-8") as f:
            dash = json.load(f)
        assert dash["panels"], "dashboard has no panels"
        exprs = [t["expr"] for p in dash["panels"] for t in p.get("targets", [])]
        assert exprs
        # every metric the dashboard queries must follow the naming scheme
        for name in re.findall(r"cerbos_tpu_[a-z0-9_]+", " ".join(exprs)):
            assert re.fullmatch(r"cerbos_tpu_[a-z0-9_]+", name)
        joined = " ".join(exprs)
        for needle in (
            "cerbos_tpu_batch_stage_seconds_bucket",
            "cerbos_tpu_batch_occupancy",
            "cerbos_tpu_breaker_state",
            "cerbos_tpu_breaker_transitions_total",
            "cerbos_tpu_xla_compile_seconds_bucket",
            "cerbos_tpu_xla_compiles_total",
            "cerbos_tpu_recompile_storms_total",
            "cerbos_tpu_xla_layout_cardinality",
            "cerbos_tpu_device_memory_bytes_in_use",
            "cerbos_tpu_readiness_state",
            # latency budget & pressure row (PR 9)
            "cerbos_tpu_request_stage_seconds_bucket",
            "cerbos_tpu_deadline_budget_remaining_seconds_bucket",
            "cerbos_tpu_decisions_total",
            "cerbos_tpu_pressure_score",
            # IPC transport row (PR 10)
            "cerbos_tpu_ipc_ring_depth",
            "cerbos_tpu_ipc_full_total",
            "cerbos_tpu_ipc_frame_bytes_bucket",
            "cerbos_tpu_ipc_client_rtt_seconds_bucket",
            # overload row (admission + brownout)
            "cerbos_tpu_admission_total",
            "cerbos_tpu_admission_inflight",
            "cerbos_tpu_admission_refusal_seconds_bucket",
            "cerbos_tpu_admission_queue_budget_total",
            "cerbos_tpu_brownout_stage",
            "cerbos_tpu_brownout_shed_total",
            "cerbos_tpu_brownout_transitions_total",
            # plan row (batched PlanResources)
            "cerbos_tpu_plan_batch_seconds_bucket",
            "cerbos_tpu_plan_queries_total",
            "cerbos_tpu_plan_residual_rules_bucket",
            "cerbos_tpu_plan_parity_checks_total",
            "cerbos_tpu_plan_parity_divergence_total",
            # provenance row (decision attribution + hot rules)
            "cerbos_tpu_rule_hits_total",
            "cerbos_tpu_decision_source_total",
        ):
            assert needle in joined, needle

    def test_all_templates_present(self):
        tdir = os.path.join(CHART_DIR, "templates")
        assert {
            "deployment.yaml",
            "service.yaml",
            "configmap.yaml",
            "_helpers.tpl",
        } <= set(os.listdir(tdir))
