"""Streaming serving path: the batcher drives submit/collect with several
device batches in flight, the pipelined check engages at realistic batch
sizes, and the fused pad+stack transfer staging is bit-exact vs the two-step
reference implementation.
"""

import concurrent.futures
import re
import time

import numpy as np
import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine.batcher import BatchingEvaluator
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input
from cerbos_tpu.tpu import TpuEvaluator
from cerbos_tpu.tpu import evaluator as evmod

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0},
        ),
        actions=["view"],
    )


def effects(outs):
    return [{a: (e.effect, e.policy) for a, e in o.actions.items()} for o in outs]


class TestStreamingBatcher:
    def test_concurrent_requests_keep_batches_in_flight(self):
        """The acceptance check: concurrent CheckResources through the
        batcher reach the device via submit/collect with >= 2 batches in
        flight, and every output is bit-exact vs the CPU oracle."""
        rt = table()
        ev = TpuEvaluator(rt, use_jax=True, min_device_batch=4)
        # max_batch=16 forces 64 requests to drain as 4+ tickets;
        # min_batch_to_wait=64 with a generous window lets the whole burst
        # queue before the first drain, so the submit loop demonstrably
        # stacks tickets instead of racing the clients
        batcher = BatchingEvaluator(
            ev,
            max_batch=16,
            max_wait_ms=500.0,
            min_batch_to_wait=64,
            max_inflight=3,
        )
        inputs = [inp(i) for i in range(64)]
        try:
            with concurrent.futures.ThreadPoolExecutor(max_workers=64) as pool:
                results = list(pool.map(lambda i: batcher.check([i])[0], inputs))
        finally:
            batcher.close()

        want = [check_input(rt, i, EvalParams()) for i in inputs]
        assert effects(results) == effects(want)
        assert batcher.stats["batches"] >= 4
        assert batcher.stats["batched_requests"] == 64
        assert batcher.stats["inflight_peak"] >= 2, batcher.stats
        assert ev.stats["device_inputs"] > 0  # the device path actually ran

    def test_sync_evaluator_fallback(self):
        """Evaluators without a streaming API still work through the batcher
        (ready tickets, no in-flight window)."""
        rt = table()

        class PlainEvaluator:
            rule_table = rt
            schema_mgr = None

            def check(self, inputs, params=None):
                return [check_input(rt, i, params or EvalParams()) for i in inputs]

        batcher = BatchingEvaluator(PlainEvaluator(), max_wait_ms=1.0)
        inputs = [inp(i) for i in range(8)]
        try:
            with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
                results = list(pool.map(lambda i: batcher.check([i])[0], inputs))
        finally:
            batcher.close()
        assert effects(results) == effects([check_input(rt, i, EvalParams()) for i in inputs])

    def test_timeout_serves_from_oracle(self):
        """A wedged device falls back to the CPU oracle per request, and the
        fallback is counted (it used to be invisible)."""
        rt = table()

        class WedgedEvaluator:
            rule_table = rt
            schema_mgr = None

            def check(self, inputs, params=None):
                time.sleep(0.5)
                return [check_input(rt, i, params or EvalParams()) for i in inputs]

        from cerbos_tpu.observability import metrics

        before = metrics().counter("cerbos_tpu_batcher_oracle_fallbacks_total").value
        batcher = BatchingEvaluator(WedgedEvaluator(), max_wait_ms=1.0, request_timeout_s=0.05)
        try:
            out = batcher.check([inp(0)])
        finally:
            batcher.close()
        assert effects(out) == effects([check_input(rt, inp(0), EvalParams())])
        assert batcher.stats["oracle_fallbacks"] == 1
        assert metrics().counter("cerbos_tpu_batcher_oracle_fallbacks_total").value == before + 1


class TestStreamingThreshold:
    @pytest.mark.parametrize("n", [63, 64, 65, 130])
    def test_parity_around_threshold(self, n):
        """check() stays bit-exact at, below and above the streaming
        threshold, and the pipelined path engages exactly at the knob."""
        rt = table()
        ev = TpuEvaluator(
            rt,
            use_jax=True,
            min_device_batch=4,
            pipeline_chunk=32,
            streaming_threshold=64,
            inflight_depth=2,
        )
        calls = []
        orig = ev._check_pipelined
        ev._check_pipelined = lambda i, p: (calls.append(len(i)), orig(i, p))[1]
        inputs = [inp(i) for i in range(n)]
        params = EvalParams()
        got = ev.check(inputs, params)
        want = [check_input(rt, i, params) for i in inputs]
        assert effects(got) == effects(want)
        assert bool(calls) == (n >= 64)

    def test_default_threshold_realistic(self):
        """ISSUE acceptance: default engagement at <= 1024 inputs."""
        assert TpuEvaluator(table(), use_jax=False).streaming_threshold <= 1024

    def test_chunking_shrinks_below_two_chunks(self):
        """Batches below 2x pipeline_chunk split into pipeline-able pieces
        instead of a single monolithic chunk."""
        rt = table()
        ev = TpuEvaluator(
            rt, use_jax=False, min_device_batch=4, pipeline_chunk=4096,
            streaming_threshold=1024, inflight_depth=3,
        )
        chunks = ev._chunk_inputs([inp(i) for i in range(1024)])
        assert len(chunks) >= 2
        assert sum(len(c) for c in chunks) == 1024
        # pow2 chunk sizes so the shrunk chunks reuse jit shape buckets
        assert all(len(c) & (len(c) - 1) == 0 for c in chunks[:-1])


class TestFusedPadStack:
    def _packed(self, n=10):
        rt = table()
        ev = TpuEvaluator(rt, use_jax=False, min_device_batch=0)
        return ev.packer.pack([inp(i) for i in range(n)], EvalParams())

    def test_matches_two_step_reference(self):
        """_pad_stack (fused, pooled, native fill) produces byte-identical
        transfer matrices to _pad_arrays + _stack_padded."""
        batch = self._packed()
        B = batch.scope_sp.shape[0]
        BA = batch.cand_cond.shape[0]
        B_pad = evmod._next_bucket(B)
        BA_pad = evmod._next_bucket(BA)
        padded = evmod._pad_arrays(
            batch, batch.columns, batch.cand_cond, batch.cand_drcond, B_pad, BA_pad
        )
        want, lay_want = evmod._stack_padded(padded)
        got, lay_got, leased = evmod._pad_stack(
            batch, batch.columns, batch.cand_cond, batch.cand_drcond, B_pad, BA_pad
        )
        try:
            assert lay_got.sig == lay_want.sig
            assert set(got) == set(want)
            for k in want:
                assert np.array_equal(np.asarray(got[k]), np.asarray(want[k])), k
        finally:
            evmod._buffer_pool.release(leased)

    def test_dirty_pool_buffers_are_fully_overwritten(self):
        """Recycled buffers carry garbage; a second fused pass over the same
        shapes must still match the freshly-allocated reference."""
        batch = self._packed()
        B_pad = evmod._next_bucket(batch.scope_sp.shape[0])
        BA_pad = evmod._next_bucket(batch.cand_cond.shape[0])
        args = (batch, batch.columns, batch.cand_cond, batch.cand_drcond, B_pad, BA_pad)
        _, _, leased = evmod._pad_stack(*args)
        for a in leased:
            a.fill(-1 if a.dtype != np.bool_ else True)  # poison
        evmod._buffer_pool.release(leased)
        want, _ = evmod._stack_padded(evmod._pad_arrays(*args))
        got, _, leased2 = evmod._pad_stack(*args)
        try:
            for k in want:
                assert np.array_equal(np.asarray(got[k]), np.asarray(want[k])), k
        finally:
            evmod._buffer_pool.release(leased2)

    def test_buffer_pool_recycles(self):
        pool = evmod._BufferPool()
        a = pool.lease((4, 8), np.int32)
        pool.release([a])
        b = pool.lease((4, 8), np.int32)
        assert b is a
        c = pool.lease((4, 8), np.int32)
        assert c is not a
        pool.release([b, c])

    def test_layout_marshalling_memoized(self):
        batch = self._packed()
        cols = batch.columns
        lay1 = evmod._marshal_layout(cols, batch.scope_sp.shape[2], cols.now_hi is not None)
        lay2 = evmod._marshal_layout(cols, batch.scope_sp.shape[2], cols.now_hi is not None)
        assert lay1 is lay2

    def test_native_stack_pad_rows(self):
        from cerbos_tpu import native as native_mod

        native = native_mod.get()
        if native is None or not hasattr(native, "stack_pad_rows"):
            pytest.skip("native extension unavailable")
        dst = np.full((3, 8), 7, dtype=np.int32)
        rows = [
            np.arange(5, dtype=np.int32),
            np.arange(8, dtype=np.int32),
            np.zeros(0, dtype=np.int32),
        ]
        native.stack_pad_rows(dst, rows)
        assert dst[0].tolist() == [0, 1, 2, 3, 4, 0, 0, 0]
        assert dst[1].tolist() == list(range(8))
        assert dst[2].tolist() == [0] * 8
        with pytest.raises(ValueError):
            native.stack_pad_rows(np.zeros((1, 2), np.int32), [np.arange(5, dtype=np.int32)])


class TestMetricsEndpoint:
    def test_batcher_metrics_visible(self, tmp_path_factory):
        """The satellite check: batcher counters reach /_cerbos/metrics."""
        import json
        import urllib.request

        from cerbos_tpu.bootstrap import initialize
        from cerbos_tpu.config import Config
        from cerbos_tpu.server.server import Server, ServerConfig

        policy_dir = tmp_path_factory.mktemp("metrics-policies")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(overrides=[f"storage.disk.directory={policy_dir}"])
        core = initialize(config)
        core.tpu_evaluator.use_jax = False  # keep the test jax-independent
        srv = Server(
            core.service,
            ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
        )
        srv.start()
        try:
            body = {
                "requestId": "m-1",
                "principal": {"id": "alice", "roles": ["user"]},
                "resources": [
                    {"actions": ["view"], "resource": {"kind": "album", "id": "a1", "attr": {"owner": "alice"}}}
                ],
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.http_port}/api/check/resources",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["results"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.http_port}/_cerbos/metrics"
            ) as resp:
                text = resp.read().decode()
        finally:
            srv.stop()
            core.close()

        m = re.search(r"^cerbos_tpu_batcher_batches_total (\d+)", text, re.M)
        assert m and int(m.group(1)) >= 1, text
        assert "cerbos_tpu_batcher_batch_size_bucket" in text
        assert "cerbos_tpu_batcher_queue_wait_seconds_bucket" in text
        assert "cerbos_tpu_batcher_inflight" in text
        # device-path fault domain metrics (docs/ROBUSTNESS.md)
        assert "cerbos_tpu_breaker_state" in text
        assert "cerbos_tpu_breaker_trips_total" in text
        assert "cerbos_tpu_batcher_deadline_drops_total" in text
        assert "cerbos_tpu_batcher_quarantined_total" in text
