import pytest

from cerbos_tpu import namer
from cerbos_tpu.policy import ParseError, parse_policy
from cerbos_tpu.policy.parser import parse_policies

RESOURCE_POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: leave_request
  version: "20210210"
  importDerivedRoles:
    - common_roles
  rules:
    - actions: ["view:*"]
      effect: EFFECT_ALLOW
      roles: [employee]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id
    - actions: ["approve"]
      effect: EFFECT_ALLOW
      derivedRoles: [direct_manager]
      condition:
        match:
          all:
            of:
              - expr: request.resource.attr.status == "PENDING_APPROVAL"
              - expr: "'GB' in request.resource.attr.geographies"
      output:
        when:
          ruleActivated: '"approved"'
"""

PRINCIPAL_POLICY = """
apiVersion: api.cerbos.dev/v1
principalPolicy:
  principal: daffy_duck
  version: dev
  rules:
    - resource: leave_request
      actions:
        - action: "*"
          effect: EFFECT_ALLOW
          name: dev_admin
"""

DERIVED_ROLES = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: common_roles
  definitions:
    - name: owner
      parentRoles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id
    - name: any_employee
      parentRoles: [employee]
"""

ROLE_POLICY = """
apiVersion: api.cerbos.dev/v1
rolePolicy:
  role: acme_admin
  scope: acme.hr
  parentRoles: [admin]
  rules:
    - resource: leave_request
      allowActions: ["view", "deny"]
"""


def test_parse_resource_policy():
    p = parse_policy(__import__("yaml").safe_load(RESOURCE_POLICY))
    rp = p.resource_policy
    assert rp is not None
    assert rp.resource == "leave_request"
    assert rp.rules[0].actions == ["view:*"]
    assert rp.rules[1].condition.match.all is not None
    assert len(rp.rules[1].condition.match.all) == 2
    assert p.fqn() == "cerbos.resource.leave_request.v20210210"
    assert p.dependencies() == [namer.derived_roles_fqn("common_roles")]


def test_parse_principal_policy():
    p = parse_policy(__import__("yaml").safe_load(PRINCIPAL_POLICY))
    assert p.principal_policy.rules[0].actions[0].action == "*"
    assert p.fqn() == "cerbos.principal.daffy_duck.vdev"


def test_parse_derived_roles():
    p = parse_policy(__import__("yaml").safe_load(DERIVED_ROLES))
    assert len(p.derived_roles.definitions) == 2
    assert p.derived_roles.definitions[1].condition is None


def test_parse_role_policy():
    p = parse_policy(__import__("yaml").safe_load(ROLE_POLICY))
    assert p.role_policy.parent_roles == ["admin"]
    assert p.fqn() == "cerbos.role.acme_admin.vdefault/acme.hr"


def test_parse_errors():
    with pytest.raises(ParseError):
        parse_policy({"apiVersion": "bogus"})
    with pytest.raises(ParseError):
        parse_policy({"apiVersion": "api.cerbos.dev/v1"})  # no policy type
    # a rule without roles or derivedRoles PARSES; rejecting it is the
    # compiler's job ("invalid resource rule", compile corpus)
    from cerbos_tpu.compile.compiler import CompileError, compile_policy

    pol = parse_policy({
        "apiVersion": "api.cerbos.dev/v1",
        "resourcePolicy": {
            "resource": "x", "version": "default",
            "rules": [{"actions": ["a"], "effect": "EFFECT_ALLOW"}],
        },
    })
    with pytest.raises(CompileError, match="does not specify any roles"):
        compile_policy(pol, {})


def test_multi_doc():
    pols = list(parse_policies(RESOURCE_POLICY + "\n---\n" + DERIVED_ROLES))
    assert len(pols) == 2


def test_unknown_fields_rejected():
    # a typo'd `conditon` must not silently produce an unconditional rule
    with pytest.raises(ParseError) as ei:
        parse_policy({
            "apiVersion": "api.cerbos.dev/v1",
            "resourcePolicy": {
                "resource": "x", "version": "default",
                "rules": [{
                    "actions": ["a"], "roles": ["r"], "effect": "EFFECT_ALLOW",
                    "conditon": {"match": {"expr": "false"}},
                }],
            },
        })
    assert "conditon" in str(ei.value)
    with pytest.raises(ParseError):
        parse_policy({"apiVersion": "api.cerbos.dev/v1", "bogusKey": 1,
                      "resourcePolicy": {"resource": "x", "version": "v"}})
