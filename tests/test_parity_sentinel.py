"""Parity sentinel: shadow-oracle sampling, divergence capture, storm policy.

The sentinel's contract (engine/sentinel.py): deterministically sample
completed device batches, replay them on the CPU oracle off the hot path,
compare effect rows bit-exactly, capture divergences into a replayable
corpus, and promote divergence storms into the lane breaker so traffic
rides the oracle (correct-over-fast). The acceptance drill — silent effect
corruption via the ``flip_effect`` fault knob detected in every serving
topology — runs here at the unit level for the single batcher, the IPC
front door, and the sharded pool.
"""

import json
import threading
import time

import pytest

from cerbos_tpu.audit.log import AuditLog, _entry_from_decision
from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import types as T
from cerbos_tpu.engine.batcher import BatchingEvaluator
from cerbos_tpu.engine.faults import FaultInjector, parse_fault_spec
from cerbos_tpu.engine.flight import recorder as flight_recorder
from cerbos_tpu.engine.health import DeviceHealth
from cerbos_tpu.engine.readiness import ReadinessState
from cerbos_tpu.engine.sentinel import (
    DivergenceCorpus,
    ParitySentinel,
    _Sample,
    compare_rows,
    effect_rows,
    from_config,
    input_from_json,
    input_to_json,
)
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

pytestmark = pytest.mark.parity_sentinel

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
        request_id=f"rq{i}",
    )


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


def flipped(outputs):
    """Hand-corrupted copies: every effect inverted (the silent-corruption
    fault the sentinel exists to catch)."""
    out = []
    for o in outputs:
        actions = {
            a: T.ActionEffect(
                effect="EFFECT_DENY" if e.effect == "EFFECT_ALLOW" else "EFFECT_ALLOW",
                policy=e.policy,
                scope=e.scope,
            )
            for a, e in o.actions.items()
        }
        out.append(
            T.CheckOutput(request_id=o.request_id, resource_id=o.resource_id, actions=actions)
        )
    return out


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class OracleEvaluator:
    """CPU-oracle-backed evaluator (the test_ipc harness shape): enough
    surface for the batcher AND the sentinel's replay capture
    (``rule_table`` / ``schema_mgr``)."""

    def __init__(self, rt):
        self.rule_table = rt
        self.schema_mgr = None

    def check(self, inputs, params=None):
        params = params or EvalParams()
        return [check_input(self.rule_table, i, params, self.schema_mgr) for i in inputs]

    # streaming surface: the batcher (and FaultInjector's delegation) probe
    # for submit/collect, so serve a pre-evaluated ticket
    def submit(self, inputs, params=None):
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


def make_sample(rt, inputs, outputs, shard=0, clock=None, health=None, batch_id=1):
    return _Sample(
        shard=shard,
        inputs=inputs,
        outputs=outputs,
        params=EvalParams(),
        rule_table=rt,
        schema_mgr=None,
        batch_id=batch_id,
        trace_ids=["t-%d" % batch_id],
        done_at=clock() if clock else time.monotonic(),
        health=health,
    )


@pytest.fixture()
def rt():
    return table()


def wait_for(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


class TestSampler:
    def test_first_batch_always_sampled(self):
        s = ParitySentinel(sample_rate=0.01, enabled=True)
        try:
            assert s.should_sample(0) is True  # acc seeded at 1.0
        finally:
            s.close()

    def test_deterministic_fraction(self):
        # rate 0.25: accumulator crossings at batches 1, 4, 8, 12, ... —
        # a pure function of the batch count, identical across instances
        picks = []
        s = ParitySentinel(sample_rate=0.25)
        try:
            picks = [i for i in range(1, 101) if s.should_sample(0)]
        finally:
            s.close()
        assert picks[:4] == [1, 4, 8, 12]
        assert len(picks) == 26  # floor(1.0 + 0.25 * 100) crossings
        s2 = ParitySentinel(sample_rate=0.25)
        try:
            assert [i for i in range(1, 101) if s2.should_sample(0)] == picks
        finally:
            s2.close()

    def test_per_shard_accumulators_are_independent(self):
        s = ParitySentinel(sample_rate=0.01)
        try:
            for _ in range(50):
                s.should_sample(0)
            # shard 1's FIRST batch is still sampled regardless of shard 0
            assert s.should_sample(1) is True
        finally:
            s.close()

    def test_disabled_and_zero_rate_never_sample(self):
        s = ParitySentinel(sample_rate=0.5, enabled=False)
        try:
            assert not s.enabled
            assert all(not s.should_sample(0) for _ in range(10))
        finally:
            s.close()
        z = ParitySentinel(sample_rate=0.0)
        try:
            assert not z.enabled
        finally:
            z.close()

    def test_rate_one_samples_every_batch(self):
        s = ParitySentinel(sample_rate=1.0)
        try:
            assert all(s.should_sample(0) for _ in range(10))
        finally:
            s.close()


class TestComparator:
    def test_identical_outputs_have_no_divergence(self, rt):
        outs = oracle(rt, [inp(i) for i in range(8)])
        assert compare_rows(effect_rows(outs), effect_rows(outs)) == []

    def test_flipped_effect_is_divergent(self, rt):
        outs = oracle(rt, [inp(i) for i in range(8)])
        bad = outs[:3] + flipped(outs[3:4]) + outs[4:]
        assert compare_rows(effect_rows(bad), effect_rows(outs)) == [3]

    def test_policy_provenance_is_compared_bit_exactly(self, rt):
        outs = oracle(rt, [inp(0)])
        rows = effect_rows(outs)
        mutated = json.loads(json.dumps(rows))
        for eff in mutated[0]["actions"].values():
            eff["policy"] = "somewhere.else"
        assert compare_rows(rows, mutated) == [0]

    def test_length_mismatch_marks_trailing_rows(self, rt):
        outs = oracle(rt, [inp(i) for i in range(4)])
        rows = effect_rows(outs)
        assert compare_rows(rows, rows[:2]) == [2, 3]
        assert compare_rows(rows[:2], rows) == [2, 3]

    def test_corpus_input_roundtrip_preserves_decisions(self, rt):
        inputs = [inp(i) for i in range(6)]
        inputs[0].aux_data = T.AuxData(jwt={"sub": "u0", "aud": ["x"]})
        rebuilt = [input_from_json(input_to_json(i)) for i in inputs]
        assert effect_rows(oracle(rt, rebuilt)) == effect_rows(oracle(rt, inputs))
        assert rebuilt[0].aux_data is not None
        assert rebuilt[0].aux_data.jwt["sub"] == "u0"


class TestDivergenceCorpus:
    def test_append_load_roundtrip(self, tmp_path):
        corpus = DivergenceCorpus(str(tmp_path), max_records=8)
        p1 = corpus.append({"shard": 0, "batch_id": 7})
        p2 = corpus.append({"shard": 1, "batch_id": 9})
        assert p1 and p2 and corpus.size() == 2
        records = DivergenceCorpus.load(str(tmp_path))
        assert [r["batch_id"] for _, r in records] == [7, 9]  # oldest first

    def test_bounded_oldest_pruned(self, tmp_path):
        corpus = DivergenceCorpus(str(tmp_path), max_records=3)
        for i in range(7):
            corpus.append({"batch_id": i})
        assert corpus.size() == 3
        assert [r["batch_id"] for _, r in DivergenceCorpus.load(str(tmp_path))] == [4, 5, 6]

    def test_unreadable_record_is_skipped(self, tmp_path):
        corpus = DivergenceCorpus(str(tmp_path), max_records=8)
        corpus.append({"batch_id": 1})
        (tmp_path / "divergence-9999999999999-000001.json").write_text("{not json")
        records = DivergenceCorpus.load(str(tmp_path))
        assert [r["batch_id"] for _, r in records] == [1]

    def test_empty_dir_disables_capture(self):
        corpus = DivergenceCorpus("", max_records=8)
        assert corpus.append({"x": 1}) is None
        assert corpus.size() == 0


class TestStormPolicy:
    """Fake-clock storm lifecycle: divergences accumulate in a sliding
    window, the threshold trips the lane breaker exactly once per window,
    and the storm clears when the window slides past."""

    def make(self, clock, tmp_path=None, threshold=2, window=10.0):
        return ParitySentinel(
            sample_rate=1.0,
            window_sec=window,
            storm_threshold=threshold,
            corpus_dir=str(tmp_path) if tmp_path else "",
            clock=clock,
        )

    def test_matching_batch_is_not_a_divergence(self, rt):
        clock = FakeClock()
        s = self.make(clock)
        try:
            outs = oracle(rt, [inp(i) for i in range(4)])
            s._verify(make_sample(rt, [inp(i) for i in range(4)], outs, clock=clock))
            assert s.stats["checks"] == 1
            assert s.stats["divergences"] == 0
            assert s.storm_shards() == []
        finally:
            s.close()

    def test_storm_trips_breaker_and_recovers(self, rt, tmp_path):
        clock = FakeClock()
        flight_recorder().clear()
        health = DeviceHealth(enabled=True, clock=clock)
        s = self.make(clock, tmp_path=tmp_path, threshold=2, window=10.0)
        try:
            inputs = [inp(0)]
            bad = flipped(oracle(rt, inputs))
            s._verify(make_sample(rt, inputs, bad, clock=clock, health=health, batch_id=1))
            # one divergence: captured but below the storm threshold
            assert s.stats["divergences"] == 1
            assert s.storm_shards() == []
            assert health.state == "closed"
            clock.advance(2.0)
            s._verify(make_sample(rt, inputs, bad, clock=clock, health=health, batch_id=2))
            # second divergence inside the window: storm — lane trips open
            assert s.stats["storms"] == 1
            assert s.storm_shards() == [0]
            assert health.state == "open"
            # a third divergence in the SAME window must not re-trip
            clock.advance(1.0)
            s._verify(make_sample(rt, inputs, bad, clock=clock, health=health, batch_id=3))
            assert s.stats["storms"] == 1
            # the corpus captured every divergence, replayably
            records = DivergenceCorpus.load(str(tmp_path))
            assert len(records) == 3
            _, rec = records[0]
            assert rec["shard"] == 0 and rec["divergent_indices"] == [0]
            assert effect_rows(oracle(rt, [input_from_json(j) for j in rec["inputs"]])) == rec[
                "oracle_effects"
            ]
            # flight recorder saw both event kinds with shard provenance
            events = flight_recorder().dump()["events"]
            kinds = [e["kind"] for e in events]
            assert "parity_divergence" in kinds and "parity_storm" in kinds
            div = next(e for e in events if e["kind"] == "parity_divergence")
            assert div["shard"] == 0 and div["batch_id"] == 1
            # recovery: the window slides past the divergences
            clock.advance(60.0)
            assert s.storm_shards() == []
        finally:
            s.close()
            flight_recorder().clear()

    def test_oracle_replay_crash_counts_as_divergence(self, rt, tmp_path):
        clock = FakeClock()
        s = self.make(clock, tmp_path=tmp_path, threshold=99)
        try:
            inputs = [inp(0)]
            outs = oracle(rt, inputs)
            sample = make_sample(rt, inputs, outs, clock=clock)
            sample.rule_table = object()  # replay against garbage → crash
            s._verify(sample)
            assert s.stats["replay_errors"] == 1
            assert s.stats["divergences"] == 1
            _, rec = DivergenceCorpus.load(str(tmp_path))[0]
            assert rec["replay_error"]
        finally:
            s.close()

    def test_readiness_degrades_with_parity_reason(self, rt):
        clock = FakeClock()
        health = DeviceHealth(enabled=False, clock=clock)
        s = self.make(clock, threshold=1, window=10.0)
        rstate = ReadinessState(clock=clock)
        rstate.bind_parity(s.storm_shards)
        try:
            assert rstate.status() == "ready"
            inputs = [inp(0)]
            s._verify(make_sample(rt, inputs, flipped(oracle(rt, inputs)), clock=clock, health=health))
            assert rstate.status() == "degraded"
            snap = rstate.snapshot()
            assert snap["reason"] == "parity"
            assert snap["parity_shards"] == [0]
            clock.advance(60.0)
            assert rstate.status() == "ready"
            assert "reason" not in rstate.snapshot()
        finally:
            s.close()


class TestSingleBatcherTopology:
    def test_flip_effect_detected_end_to_end(self, rt, tmp_path):
        """The acceptance drill, single-batcher form: a silently corrupting
        device path answers requests normally (no errors, no timeouts) and
        the sentinel is the ONLY mechanism that notices."""
        faulty = FaultInjector(OracleEvaluator(rt), "flip_effect:1.0")
        batcher = BatchingEvaluator(faulty, max_wait_ms=0.0)
        sentinel = ParitySentinel(
            sample_rate=1.0, storm_threshold=99, corpus_dir=str(tmp_path)
        ).attach(batcher)
        try:
            outs = batcher.check([inp(i) for i in range(4)])
            assert len(outs) == 4  # requests answered (wrongly) — not lost
            assert sentinel.drain(timeout=10.0)
            assert sentinel.stats["checks"] >= 1
            assert sentinel.stats["divergences"] >= 1
            assert sentinel.snapshot()["corpus_records"] >= 1
        finally:
            sentinel.close()
            batcher.close()

    def test_healthy_batcher_has_zero_divergences(self, rt):
        batcher = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=0.0)
        sentinel = ParitySentinel(sample_rate=1.0, storm_threshold=99).attach(batcher)
        try:
            for i in range(6):
                batcher.check([inp(i)])
            assert sentinel.drain(timeout=10.0)
            assert sentinel.stats["checks"] >= 1
            assert sentinel.stats["divergences"] == 0
        finally:
            sentinel.close()
            batcher.close()

    def test_unsampled_batches_never_enqueue(self, rt):
        batcher = BatchingEvaluator(OracleEvaluator(rt), max_wait_ms=0.0)
        sentinel = ParitySentinel(sample_rate=1.0, enabled=False).attach(batcher)
        try:
            batcher.check([inp(0)])
            assert sentinel.backlog() == 0
            assert sentinel.stats["sampled"] == 0
        finally:
            sentinel.close()
            batcher.close()


class TestIpcTopology:
    def test_sentinel_samples_in_the_batcher_process(self, rt, tmp_path):
        """``--frontends N`` topology: the sentinel rides the shared-batcher
        process (where the device is); front-end tickets crossing the unix
        socket are covered without any front-end wiring."""
        from cerbos_tpu.engine.ipc import BatcherIpcServer, RemoteBatcherClient

        faulty = FaultInjector(OracleEvaluator(rt), "flip_effect:1.0")
        batcher = BatchingEvaluator(faulty, max_wait_ms=1.0)
        sentinel = ParitySentinel(sample_rate=1.0, storm_threshold=99).attach(batcher)
        server = BatcherIpcServer(str(tmp_path / "batcher.sock"), batcher)
        server.start()
        client = RemoteBatcherClient(
            server.socket_path,
            rt,
            request_timeout_s=10.0,
            worker_label="fe-test",
            status_poll_s=0.05,
            connect_retry_s=0.05,
        )
        try:
            assert wait_for(client._connected.is_set)
            outs = client.check([inp(i) for i in range(8)])
            assert len(outs) == 8
            assert sentinel.drain(timeout=10.0)
            assert sentinel.stats["divergences"] >= 1
        finally:
            client.close()
            server.close()
            sentinel.close()
            batcher.close()


class TestShardedTopology:
    def test_flip_effect_storm_trips_only_the_sick_shard(self, rt, tmp_path):
        """The acceptance drill, sharded form: ``flip_effect:1.0,shard:0``
        corrupts ONE lane silently; the sentinel detects it, storms, and
        trips shard 0's breaker while shard 1 keeps serving — zero requests
        lost."""
        from cerbos_tpu.engine.shards import build_shard_pool
        from cerbos_tpu.tpu.evaluator import TpuEvaluator

        base = TpuEvaluator(rt, use_jax=False, min_device_batch=1)
        pool = build_shard_pool(
            base,
            n_shards=2,
            routing="round_robin",
            max_wait_ms=0.0,
            request_timeout_s=10.0,
            fault_spec="flip_effect:1.0,shard:0",
        )
        sentinel = ParitySentinel(
            sample_rate=1.0, storm_threshold=1, corpus_dir=str(tmp_path)
        ).attach(pool)
        try:
            assert all(lane.sentinel is sentinel for lane in pool.shards)
            answered = 0
            for i in range(12):
                answered += len(pool.check([inp(i)]))
            assert answered == 12  # zero lost requests
            assert sentinel.drain(timeout=10.0)
            assert wait_for(lambda: sentinel.stats["storms"] >= 1)
            snap = sentinel.snapshot()
            # divergences are shard 0's alone; shard 1's checks all pass
            assert snap["divergences"] >= 1
            assert sentinel.storm_shards() == [0]
            assert pool.shards[0].health.state == "open"
            assert pool.shards[1].health.state == "closed"
            assert snap["lanes"][1]["sampled"] >= 1
            # corpus records carry shard-0 provenance for offline replay
            for _, rec in DivergenceCorpus.load(str(tmp_path)):
                assert rec["shard"] == 0
        finally:
            sentinel.close()
            pool.close()


class TestAuditTraceCorrelation:
    def test_decision_entries_carry_trace_and_shard(self, rt):
        inputs = [inp(0)]
        outputs = oracle(rt, inputs)
        entry = _entry_from_decision("c1", inputs, outputs, trace_id="abc123", shard=3)
        assert entry["traceId"] == "abc123"
        assert entry["shard"] == 3
        # shard 0 is a real shard id, not an empty value to drop
        assert _entry_from_decision("c2", inputs, outputs, trace_id="t", shard=0)["shard"] == 0
        bare = _entry_from_decision("c3", inputs, outputs)
        assert "traceId" not in bare and "shard" not in bare

    def test_write_decision_never_blocks_on_a_wedged_backend(self, rt):
        release = threading.Event()
        written = []

        class WedgedBackend:
            def write(self, entry):
                release.wait(timeout=30)
                written.append(entry)

        log = AuditLog(backend=WedgedBackend())
        inputs = [inp(0)]
        outputs = oracle(rt, inputs)
        try:
            t0 = time.perf_counter()
            # queue bound is 4096: overflow it while the writer is wedged
            for i in range(5000):
                log.write_decision(f"c{i}", inputs, outputs, trace_id="t", shard=0)
            elapsed = time.perf_counter() - t0
            assert elapsed < 5.0  # drops, never blocks the hot path
            assert log._queue.qsize() >= 4095
        finally:
            release.set()
            log.close()
        assert written  # the writer drained once unwedged


class TestServerIntegration:
    def test_bootstrap_attaches_sentinel_and_flight_shard_filter(self, tmp_path_factory):
        """Bootstrap wires the sentinel onto the real batcher, and the flight
        endpoint narrows to one lane via ``?shard=N`` (non-int → 400)."""
        import urllib.error
        import urllib.request

        from cerbos_tpu.bootstrap import initialize
        from cerbos_tpu.config import Config
        from cerbos_tpu.server.server import Server, ServerConfig

        policy_dir = tmp_path_factory.mktemp("parity-policies")
        (policy_dir / "album.yaml").write_text(POLICY)
        config = Config.load(overrides=[f"storage.disk.directory={policy_dir}"])
        core = initialize(config)
        core.tpu_evaluator.use_jax = False  # keep the test jax-independent
        srv = Server(
            core.service,
            ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
        )
        srv.start()
        try:
            assert core.sentinel is not None and core.sentinel.enabled
            assert core.batcher.sentinel is core.sentinel
            body = {
                "requestId": "ps-1",
                "principal": {"id": "alice", "roles": ["user"]},
                "resources": [
                    {
                        "actions": ["view"],
                        "resource": {"kind": "album", "id": "a1", "attr": {"owner": "alice"}},
                    }
                ],
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.http_port}/api/check/resources",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["results"]

            def flight(q=""):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.http_port}/_cerbos/debug/flight{q}"
                ) as resp:
                    return json.loads(resp.read())

            assert flight()["batches"]  # the request produced a batch record
            mine = flight("?shard=0")
            assert mine["shard_filter"] == 0 and mine["batches"]
            other = flight("?shard=7")
            assert other["shard_filter"] == 7 and other["batches"] == []
            with pytest.raises(urllib.error.HTTPError) as err:
                flight("?shard=bogus")
            assert err.value.code == 400
        finally:
            srv.stop()
            core.close()


class TestConfigAndFaultGrammar:
    def test_from_config_reads_the_knob_block(self, tmp_path):
        s = from_config(
            {
                "enabled": True,
                "sampleRate": 0.5,
                "windowSec": 7,
                "stormThreshold": 9,
                "corpusDir": str(tmp_path / "corpus"),
                "corpusMax": 5,
            }
        )
        try:
            assert s.enabled and s.sample_rate == 0.5
            assert s.window_sec == 7.0 and s.storm_threshold == 9
            assert s.corpus.dir == str(tmp_path / "corpus")
            assert s.corpus.max_records == 5
        finally:
            s.close()
        off = from_config({"enabled": False})
        try:
            assert not off.enabled
        finally:
            off.close()

    def test_flip_effect_knob_parses_and_flips(self, rt):
        knobs = parse_fault_spec("flip_effect:1.0,shard:0")
        assert knobs["flip_effect"] == 1.0 and knobs["shard"] == 0
        faulty = FaultInjector(OracleEvaluator(rt), "flip_effect:1.0")
        inputs = [inp(i) for i in range(4)]
        device = effect_rows(faulty.check(inputs))
        clean = effect_rows(oracle(rt, inputs))
        assert compare_rows(device, clean) == [0, 1, 2, 3]
        # the injector corrupts silently: same rows, same actions, flipped
        # effects only — exactly the failure the breaker can never see
        for bad, good in zip(device, clean):
            assert bad["resourceId"] == good["resourceId"]
            assert set(bad["actions"]) == set(good["actions"])

    def test_flip_effect_zero_probability_is_inert(self, rt):
        faulty = FaultInjector(OracleEvaluator(rt), "flip_effect:0.0")
        inputs = [inp(i) for i in range(4)]
        assert effect_rows(faulty.check(inputs)) == effect_rows(oracle(rt, inputs))
