"""End-to-end deploy test: the Helm chart's rendered config boots the real
server (the Dockerfile entrypoint command), serves gRPC + HTTP + HTTPS,
hot-rotates its TLS cert, and hot-reloads policies — all process-level, no
network egress (ref: e2e/run.sh + internal/test/e2e, kind/Helm scenarios).
"""

import datetime
import json
import os
import socket
import ssl
import subprocess
import sys
import time
import urllib.request

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deploy", "charts", "cerbos-tpu")

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.public == true
"""

POLICY_EXTRA = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: track
  version: default
  rules:
    - actions: ["play"]
      effect: EFFECT_ALLOW
      roles: [listener]
"""

CHECK_BODY = {
    "requestId": "e2e-1",
    "principal": {"id": "alice", "roles": ["user"]},
    "resources": [
        {"actions": ["view"], "resource": {"kind": "album", "id": "a1", "attr": {"public": True}}}
    ],
}


def _self_signed_cert(cn: str):
    """(cert_pem, key_pem) self-signed for 127.0.0.1."""
    import ipaddress

    pytest.importorskip("cryptography", reason="TLS tests need cert generation")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=1))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName("localhost"), x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    return (
        cert.public_bytes(serialization.Encoding.PEM),
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        ),
    )


def render_chart_config(tls_secret: bool) -> dict:
    """The configmap template's logic in Python: chart values →
    /config/config.yaml content (values.yaml cerbos.config + the
    tls.secretName injection the template performs)."""
    with open(os.path.join(CHART, "values.yaml")) as f:
        values = yaml.safe_load(f)
    config = values["cerbos"]["config"]
    if tls_secret:
        config.setdefault("server", {})["tls"] = {"cert": "/tls/tls.crt", "key": "/tls/tls.key"}
    return config


def test_chart_renders_loadable_config():
    config = render_chart_config(tls_secret=True)
    assert config["server"]["httpListenAddr"]
    assert config["storage"]["driver"] == "disk"
    assert config["server"]["tls"]["cert"] == "/tls/tls.crt"
    # every chart template must at least be valid YAML after stripping go
    # templating from the metadata (the config payload itself carries none)
    for name in os.listdir(os.path.join(CHART, "templates")):
        assert name.endswith((".yaml", ".tpl"))


class _Pdp:
    def __init__(self, proc, http_port, grpc_port, policy_dir, tls_dir):
        self.proc = proc
        self.http_port = http_port
        self.grpc_port = grpc_port
        self.policy_dir = policy_dir
        self.tls_dir = tls_dir


@pytest.fixture(scope="module")
def pdp(tmp_path_factory):
    """Boot the PDP the way the container does: the chart's rendered config
    + the Dockerfile ENTRYPOINT command (cerbos-tpu server --config ...)."""
    root = tmp_path_factory.mktemp("e2e")
    policy_dir = root / "policies"
    policy_dir.mkdir()
    (policy_dir / "album.yaml").write_text(POLICY)
    tls_dir = root / "tls"
    tls_dir.mkdir()
    cert, key = _self_signed_cert("cerbos-e2e")
    (tls_dir / "tls.crt").write_bytes(cert)
    (tls_dir / "tls.key").write_bytes(key)

    config = render_chart_config(tls_secret=True)
    # the chart mounts these absolute paths; the process-level harness
    # rebinds them into the sandbox (and uses ephemeral ports)
    config["server"]["httpListenAddr"] = "127.0.0.1:0"
    config["server"]["grpcListenAddr"] = "127.0.0.1:0"
    config["server"]["tls"] = {
        "cert": str(tls_dir / "tls.crt"),
        "key": str(tls_dir / "tls.key"),
        "watchInterval": 0.3,
    }
    config["storage"]["disk"]["directory"] = str(policy_dir)
    config["storage"]["disk"]["pollInterval"] = 0.3
    config["engine"]["tpu"]["enabled"] = False  # CPU oracle: no jax needed
    cfg_path = root / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(config))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "cerbos_tpu.cli", "server", "--config", str(cfg_path)],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
        cwd=REPO,
    )
    http_port = grpc_port = 0
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("cerbos-tpu serving:"):
            for tok in line.split():
                if tok.startswith("http="):
                    http_port = int(tok.split("=")[1])
                elif tok.startswith("grpc="):
                    grpc_port = int(tok.split("=")[1])
            break
    assert http_port and grpc_port, "server never announced"
    handle = _Pdp(proc, http_port, grpc_port, policy_dir, tls_dir)
    _wait_ready(handle)
    yield handle
    proc.terminate()
    proc.wait(timeout=15)


def _tls_context(handle) -> ssl.SSLContext:
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE  # self-signed; identity asserted via serial checks
    return ctx


def _https_post(handle, path, body, timeout=5.0):
    req = urllib.request.Request(
        f"https://127.0.0.1:{handle.http_port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout, context=_tls_context(handle)) as resp:
        return json.loads(resp.read())


def _wait_ready(handle, timeout=60.0):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            req = urllib.request.Request(f"https://127.0.0.1:{handle.http_port}/_cerbos/health")
            with urllib.request.urlopen(req, timeout=2, context=_tls_context(handle)) as resp:
                if resp.status == 200:
                    return
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.25)
    raise AssertionError(f"PDP never became healthy: {last}")


def test_https_check(pdp):
    resp = _https_post(pdp, "/api/check/resources", CHECK_BODY)
    assert resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW"
    deny = dict(CHECK_BODY)
    deny["resources"] = [
        {"actions": ["view"], "resource": {"kind": "album", "id": "a2", "attr": {"public": False}}}
    ]
    resp = _https_post(pdp, "/api/check/resources", deny)
    assert resp["results"][0]["actions"]["view"] == "EFFECT_DENY"


def test_grpc_tls_check(pdp):
    import grpc

    from cerbos_tpu.api.cerbos.request.v1 import request_pb2
    from cerbos_tpu.api.cerbos.response.v1 import response_pb2
    from google.protobuf import json_format

    creds = grpc.ssl_channel_credentials(root_certificates=(pdp.tls_dir / "tls.crt").read_bytes())
    with grpc.secure_channel(
        f"127.0.0.1:{pdp.grpc_port}", creds,
        options=(("grpc.ssl_target_name_override", "localhost"),),
    ) as ch:
        stub = ch.unary_unary(
            "/cerbos.svc.v1.CerbosService/CheckResources",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=response_pb2.CheckResourcesResponse.FromString,
        )
        req = json_format.ParseDict(CHECK_BODY, request_pb2.CheckResourcesRequest(), ignore_unknown_fields=True)
        resp = stub(req, timeout=10)
        assert resp.results[0].actions["view"] == 1  # EFFECT_ALLOW


def _server_cert_serial(handle) -> int:
    pytest.importorskip("cryptography", reason="TLS tests need cert parsing")
    from cryptography import x509

    ctx = _tls_context(handle)
    with socket.create_connection(("127.0.0.1", handle.http_port), timeout=5) as sock:
        with ctx.wrap_socket(sock, server_hostname="localhost") as tls:
            der = tls.getpeercert(binary_form=True)
    return x509.load_der_x509_certificate(der).serial_number


def test_tls_cert_hot_rotation(pdp):
    serial_before = _server_cert_serial(pdp)
    cert, key = _self_signed_cert("cerbos-e2e-rotated")
    (pdp.tls_dir / "tls.crt").write_bytes(cert)
    (pdp.tls_dir / "tls.key").write_bytes(key)
    deadline = time.time() + 20
    while time.time() < deadline:
        if _server_cert_serial(pdp) != serial_before:
            break
        time.sleep(0.3)
    else:
        raise AssertionError("server never picked up the rotated certificate")
    # still serving after rotation
    resp = _https_post(pdp, "/api/check/resources", CHECK_BODY)
    assert resp["results"][0]["actions"]["view"] == "EFFECT_ALLOW"


def test_policy_hot_reload(pdp):
    body = {
        "requestId": "e2e-2",
        "principal": {"id": "bob", "roles": ["listener"]},
        "resources": [{"actions": ["play"], "resource": {"kind": "track", "id": "t1"}}],
    }
    resp = _https_post(pdp, "/api/check/resources", body)
    assert resp["results"][0]["actions"]["play"] == "EFFECT_DENY"  # unknown kind
    (pdp.policy_dir / "track.yaml").write_text(POLICY_EXTRA)
    deadline = time.time() + 20
    while time.time() < deadline:
        resp = _https_post(pdp, "/api/check/resources", body)
        if resp["results"][0]["actions"]["play"] == "EFFECT_ALLOW":
            break
        time.sleep(0.3)
    else:
        raise AssertionError("policy change never took effect")
