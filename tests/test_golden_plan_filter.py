"""Reference query_planner_filter corpus: filter normalisation + rendering.

Mirrors internal/ruletable/planner/planner_test.go TestNormaliseFilter: each
case feeds a PlanResources filter through normalisation and compares the
resulting (kind, condition) protojson shape and the FilterToString debug
rendering byte-for-byte.
"""

import os

import pytest
import yaml

from cerbos_tpu.plan.normalize import filter_to_string, normalise_filter
from cerbos_tpu.plan.types import Expr, Operand

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "query_planner_filter")

CASES = sorted(f for f in os.listdir(CORPUS) if f.endswith(".yaml"))


def operand_from(d: dict) -> Operand:
    if "expression" in d:
        e = d["expression"]
        return Operand(
            expression=Expr(
                op=e.get("operator", ""),
                operands=[operand_from(o) for o in e.get("operands", [])],
            )
        )
    if "variable" in d:
        return Operand(variable=d["variable"])
    return Operand(value=d.get("value"))


def _norm(v):
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items())}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(v, (int, float)):
        return float(v)
    return v


@pytest.mark.parametrize("case", CASES)
def test_normalise_filter(case):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = yaml.safe_load(f)
    inp = tc["input"]
    cond = operand_from(inp["condition"]) if inp.get("condition") else None
    kind, norm_cond = normalise_filter(inp.get("kind", "KIND_UNSPECIFIED"), cond)

    want = tc["wantFilter"]
    have = {"kind": kind}
    if norm_cond is not None:
        have["condition"] = norm_cond.to_json()
    assert _norm(want) == _norm(have), case
    assert tc["wantString"] == filter_to_string(kind, norm_cond), case
