"""Compile-economy observability (docs/OBSERVABILITY.md, "Compile economy").

Covers the PR's acceptance criteria end to end on the CPU jax backend:

- a cold ``check()`` on a fresh evaluator records exactly one compile (with
  nonzero wall time) and one jit-cache miss; a second same-layout batch is
  a pure cache hit with zero new compiles;
- the recompile-storm detector trips once per excursion under a fake clock;
- readiness transitions warming -> ready -> degraded-but-live, and the
  ``/_cerbos/ready`` + gRPC health surfaces gate traffic accordingly;
- the warmup driver pre-compiles one layout per batch size and always
  opens readiness, even on failure;
- ``jitcache.status()`` reports the directory and warm evidence, and
  repeat ``enable()`` calls return the directory instead of None;
- the profiler endpoint is operator-gated, serialized, and bounded.
"""

import json
import os
import urllib.error
import urllib.request

import grpc
import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, Principal, Resource
from cerbos_tpu.engine.flight import recorder as flight_recorder
from cerbos_tpu.engine.readiness import ReadinessState, state as readiness_state
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.tpu import TpuEvaluator
from cerbos_tpu.tpu import compilestats, jitcache, profiler
from cerbos_tpu.tpu.compilestats import CompileStats, RecompileStormDetector
from cerbos_tpu.tpu.warmup import WarmupDriver, derive_corpus, synthetic_inputs

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inputs(n: int) -> list:
    return [
        CheckInput(
            principal=Principal(id=f"u{i}", roles=["user"]),
            resource=Resource(kind="album", id=f"a{i}", attr={"owner": f"u{i % 7}"}),
            actions=["view"],
            request_id=f"r{i}",
        )
        for i in range(n)
    ]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# -- acceptance: compile accounting on the real device path -----------------


class TestCompileAccounting:
    def test_cold_check_records_one_compile_then_pure_hits(self):
        """ISSUE acceptance: cold check() = exactly one compile with nonzero
        latency + one miss; second same-layout batch = one hit, no compile.
        The stats are process-global, so every assertion is a delta."""
        ev = TpuEvaluator(table(), use_jax=True, min_device_batch=4)
        before = compilestats.stats().snapshot()

        out = ev.check(inputs(16))
        mid = compilestats.stats().snapshot()
        assert len(out) == 16
        assert mid["compiles"] - before["compiles"] == 1
        assert mid["cache_misses"] - before["cache_misses"] == 1
        assert mid["cache_hits"] - before["cache_hits"] == 0
        assert mid["compile_seconds_total"] > before["compile_seconds_total"]

        out2 = ev.check(inputs(16))
        after = compilestats.stats().snapshot()
        assert len(out2) == 16
        assert after["compiles"] - mid["compiles"] == 0
        assert after["cache_hits"] - mid["cache_hits"] == 1
        assert after["cache_misses"] - mid["cache_misses"] == 0

    def test_distinct_shape_buckets_are_distinct_layouts(self):
        ev = TpuEvaluator(table(), use_jax=True, min_device_batch=4)
        before = compilestats.stats().snapshot()
        ev.check(inputs(16))
        ev.check(inputs(32))
        after = compilestats.stats().snapshot()
        assert after["compiles"] - before["compiles"] == 2
        per = after["per_layout_compiles"]
        assert per.get("B16xBA16", 0) >= 1
        assert per.get("B32xBA32", 0) >= 1

    def test_oracle_path_compiles_nothing(self):
        ev = TpuEvaluator(table(), use_jax=True, min_device_batch=64)
        before = compilestats.stats().snapshot()
        ev.check(inputs(8))  # below min_device_batch: serial oracle
        after = compilestats.stats().snapshot()
        assert after["compiles"] == before["compiles"]
        assert after["cache_misses"] == before["cache_misses"]


# -- recompile-storm detector ------------------------------------------------


class TestStormDetector:
    def test_trips_once_at_threshold(self):
        clk = FakeClock()
        det = RecompileStormDetector(threshold=3, window_s=60.0, clock=clk)
        assert det.observe("L1") is None
        assert det.observe("L2") is None
        assert det.observe("L3") == 3
        assert det.storms == 1

    def test_sustained_storm_is_one_event(self):
        clk = FakeClock()
        det = RecompileStormDetector(threshold=3, window_s=60.0, clock=clk)
        for k in ("L1", "L2", "L3", "L4", "L5", "L6"):
            det.observe(k)
            clk.advance(1.0)
        assert det.storms == 1

    def test_repeat_compiles_of_one_layout_never_storm(self):
        clk = FakeClock()
        det = RecompileStormDetector(threshold=3, window_s=60.0, clock=clk)
        for _ in range(50):
            assert det.observe("L1") is None
            clk.advance(0.5)
        assert det.storms == 0

    def test_rearms_after_window_drains(self):
        clk = FakeClock()
        det = RecompileStormDetector(threshold=3, window_s=60.0, clock=clk)
        for k in ("L1", "L2", "L3"):
            det.observe(k)
        assert det.storms == 1
        clk.advance(120.0)  # old events age out entirely
        assert det.observe("M1") is None  # distinct fell below threshold: re-armed
        assert det.observe("M2") is None
        assert det.observe("M3") == 3
        assert det.storms == 2

    def test_window_prunes_old_events(self):
        clk = FakeClock()
        det = RecompileStormDetector(threshold=3, window_s=10.0, clock=clk)
        det.observe("L1")
        clk.advance(11.0)
        det.observe("L2")
        clk.advance(11.0)
        # never 3 distinct within any 10s window
        assert det.observe("L3") is None
        assert det.storms == 0

    def test_stats_storm_increments_counter_and_flight_event(self):
        clk = FakeClock()
        st = CompileStats(clock=clk, storm_threshold=2, storm_window_s=30.0)

        def storm_events():
            return [
                e for e in flight_recorder().dump()["events"] if e["kind"] == "recompile_storm"
            ]

        n_before = len(storm_events())
        st.record_compile("B16xBA16", 0.1, trace_key=("a",))
        st.record_compile("B32xBA32", 0.1, trace_key=("b",))
        assert st.snapshot()["storms"] == 1
        storms = storm_events()
        assert len(storms) == n_before + 1
        assert storms[-1]["distinct"] == 2
        assert storms[-1]["threshold"] == 2

    def test_configure_rebinds_global_detector_in_place(self):
        det = compilestats.stats().detector
        old_thr, old_win = det.threshold, det.window_s
        try:
            compilestats.configure(storm_threshold=99, storm_window_s=7.0)
            assert compilestats.stats().detector is det
            assert det.threshold == 99
            assert det.window_s == 7.0
        finally:
            compilestats.configure(storm_threshold=old_thr, storm_window_s=old_win)


# -- readiness state machine -------------------------------------------------


class TestReadiness:
    def test_born_ready(self):
        rs = ReadinessState(clock=FakeClock())
        assert rs.status() == "ready"
        assert rs.serving()
        assert rs.snapshot() == {"status": "ready", "compiled_layouts": 0, "expected": 0}

    def test_warming_to_ready(self):
        rs = ReadinessState(clock=FakeClock())
        rs.begin_warmup(expected=2)
        assert rs.status() == "warming"
        assert not rs.serving()
        rs.layout_compiled()
        assert rs.status() == "warming"  # partial warmup still gates
        rs.layout_compiled()
        rs.mark_ready()
        assert rs.status() == "ready"
        assert rs.serving()
        assert rs.snapshot() == {"status": "ready", "compiled_layouts": 2, "expected": 2}

    def test_failed_warmup_still_opens_with_error_recorded(self):
        rs = ReadinessState(clock=FakeClock())
        rs.begin_warmup(expected=3)
        rs.mark_ready(error="size 64: device fell over")
        snap = rs.snapshot()
        assert snap["status"] == "ready"
        assert snap["warmup_error"] == "size 64: device fell over"

    def test_open_breaker_degrades_but_keeps_serving(self):
        rs = ReadinessState(clock=FakeClock())
        rs.bind_health(lambda: "open")
        assert rs.status() == "degraded"
        assert rs.serving()  # degraded-but-live beats a restart loop
        rs.bind_health(lambda: "closed")
        assert rs.status() == "ready"

    def test_breaker_never_masks_warming(self):
        rs = ReadinessState(clock=FakeClock())
        rs.bind_health(lambda: "open")
        rs.begin_warmup(expected=1)
        assert rs.status() == "warming"
        assert not rs.serving()

    def test_broken_health_provider_is_ignored(self):
        rs = ReadinessState(clock=FakeClock())

        def boom():
            raise RuntimeError("no breaker yet")

        rs.bind_health(boom)
        assert rs.status() == "ready"


# -- warmup driver ------------------------------------------------------------


class TestWarmup:
    def test_derive_corpus_from_rule_table(self):
        specs = derive_corpus(table())
        # the admin rule's "*" action is skipped but its role still counts
        assert specs == [{"kind": "album", "actions": ["view"], "roles": ["admin", "user"]}]

    def test_derive_corpus_fallback_when_unreadable(self):
        specs = derive_corpus(object())
        assert specs == [{"kind": "warmup", "actions": ["view"], "roles": ["user"]}]

    def test_synthetic_inputs_shape(self):
        specs = [{"kind": "album", "actions": ["view"], "roles": ["user"]}]
        ins = synthetic_inputs(specs, 5)
        assert len(ins) == 5
        assert {i.resource.kind for i in ins} == {"album"}
        assert ins[0].request_id == "warmup-0"
        assert ins[0].principal.roles == ["user"]

    def test_driver_warms_each_size_and_opens_readiness(self):
        rs = ReadinessState(clock=FakeClock())
        ev = TpuEvaluator(table(), use_jax=False, min_device_batch=4)
        driver = WarmupDriver(ev, batch_sizes=[2, 8], readiness=rs)
        # 2 clamps up to min_device_batch=4: the oracle path compiles nothing
        assert driver.batch_sizes == [4, 8]
        assert driver.expected == 2
        rs.begin_warmup(expected=driver.expected)
        assert not rs.serving()
        summary = driver.run()
        assert summary["layouts"] == 2
        assert summary["inputs"] == 12
        assert summary["errors"] == []
        assert rs.serving()
        assert rs.snapshot() == {"status": "ready", "compiled_layouts": 2, "expected": 2}

    def test_driver_failure_still_marks_ready(self):
        class Exploding:
            min_device_batch = 4
            rule_table = None

            def check(self, inputs):
                raise RuntimeError("device on fire")

        rs = ReadinessState(clock=FakeClock())
        rs.begin_warmup(expected=1)
        driver = WarmupDriver(Exploding(), batch_sizes=[4], corpus=[{"kind": "x"}], readiness=rs)
        summary = driver.run()
        assert summary["layouts"] == 0
        assert len(summary["errors"]) == 1
        snap = rs.snapshot()
        assert snap["status"] == "ready"  # never wedge readiness shut
        assert "device on fire" in snap["warmup_error"]

    def test_background_thread_reports_in(self):
        rs = ReadinessState(clock=FakeClock())
        ev = TpuEvaluator(table(), use_jax=False, min_device_batch=4)
        driver = WarmupDriver(ev, batch_sizes=[4], readiness=rs)
        rs.begin_warmup(expected=driver.expected)
        t = driver.start()
        t.join(timeout=30)
        assert not t.is_alive()
        assert rs.snapshot()["status"] == "ready"


# -- jitcache status ----------------------------------------------------------


@pytest.fixture
def jitcache_state():
    saved = (jitcache._enabled, jitcache._external, jitcache._entries_at_enable)
    yield
    jitcache._enabled, jitcache._external, jitcache._entries_at_enable = saved


class TestJitcacheStatus:
    def test_repeat_enable_returns_directory_not_none(self, jitcache_state, tmp_path):
        # the pre-fix behavior returned None on every call after the first,
        # leaving bootstrap logging "cache: None" for a perfectly live cache
        jitcache._enabled = str(tmp_path)
        jitcache._external = False
        assert jitcache.enable() == str(tmp_path)
        assert jitcache.enable() == str(tmp_path)

    def test_entry_count_counts_files(self, jitcache_state, tmp_path):
        jitcache._enabled = str(tmp_path)
        assert jitcache.entry_count() == 0
        for i in range(3):
            (tmp_path / f"entry-{i}").write_bytes(b"x")
        (tmp_path / "subdir").mkdir()  # directories are not cache entries
        assert jitcache.entry_count() == 3

    def test_entry_count_none_when_disabled(self, jitcache_state):
        jitcache._enabled = False
        assert jitcache.entry_count() is None
        assert jitcache.directory() is None

    def test_status_reports_warm_evidence(self, jitcache_state, tmp_path):
        (tmp_path / "warm-entry").write_bytes(b"x")
        jitcache._enabled = str(tmp_path)
        jitcache._external = True
        jitcache._entries_at_enable = 1
        st = jitcache.status()
        assert st["enabled"] is True
        assert st["dir"] == str(tmp_path)
        assert st["external"] is True
        assert st["entries"] == 1
        assert st["warm_at_enable"] is True
        assert isinstance(st["persistent_loads"], int)

    def test_status_when_disabled(self, jitcache_state):
        jitcache._enabled = False
        jitcache._external = False
        jitcache._entries_at_enable = None
        st = jitcache.status()
        assert st["enabled"] is False
        assert st["dir"] is None
        assert st["warm_at_enable"] is False


# -- profiler -----------------------------------------------------------------


@pytest.fixture
def profiler_config(tmp_path):
    yield tmp_path
    profiler.configure()  # back to disabled defaults


class TestProfiler:
    def test_disabled_by_default(self, profiler_config):
        profiler.configure()
        assert not profiler.enabled()
        with pytest.raises(profiler.ProfilerDisabled):
            profiler.capture(1)

    def test_bad_duration_rejected(self, profiler_config):
        profiler.configure(enabled=True, dir=str(profiler_config))
        with pytest.raises(ValueError):
            profiler.capture(0)
        with pytest.raises(ValueError):
            profiler.capture(-3)

    def test_capture_clamps_and_writes_artifact_dir(self, profiler_config, monkeypatch):
        profiler.configure(enabled=True, dir=str(profiler_config), max_seconds=0.25)
        captured = {}

        def fake_trace(path, seconds):
            captured["seconds"] = seconds
            os.makedirs(path, exist_ok=True)

        monkeypatch.setattr(profiler, "_run_trace", fake_trace)
        artifact = profiler.capture(999)
        assert captured["seconds"] == 0.25  # clamped to maxSeconds
        assert artifact["seconds"] == 0.25
        assert os.path.isdir(artifact["path"])
        assert os.path.dirname(artifact["path"]) == str(profiler_config)

    def test_artifact_dir_is_bounded(self, profiler_config, monkeypatch):
        profiler.configure(enabled=True, dir=str(profiler_config), max_artifacts=2)
        monkeypatch.setattr(
            profiler, "_run_trace", lambda path, seconds: os.makedirs(path, exist_ok=True)
        )
        paths = [profiler.capture(0.01)["path"] for _ in range(5)]
        remaining = sorted(os.listdir(profiler_config))
        assert len(remaining) == 2
        # the newest captures survive the prune
        assert remaining == sorted(os.path.basename(p) for p in paths[-2:])

    def test_one_capture_at_a_time(self, profiler_config):
        profiler.configure(enabled=True, dir=str(profiler_config))
        with profiler._lock:
            profiler._active = True
        try:
            with pytest.raises(profiler.ProfilerBusy):
                profiler.capture(0.01)
        finally:
            with profiler._lock:
                profiler._active = False


# -- server surfaces: /_cerbos/ready, gRPC health, flight header, profile ----


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    from cerbos_tpu.bootstrap import initialize
    from cerbos_tpu.config import Config
    from cerbos_tpu.server.server import Server, ServerConfig

    policy_dir = tmp_path_factory.mktemp("policies")
    (policy_dir / "album.yaml").write_text(POLICY)
    config = Config.load(
        overrides=[
            f"storage.disk.directory={policy_dir}",
            "server.httpListenAddr=127.0.0.1:0",
            "server.grpcListenAddr=127.0.0.1:0",
            # readiness surfaces don't need a device; the oracle path keeps
            # this module independent of jax backend startup
            "engine.tpu.enabled=false",
        ]
    )
    core = initialize(config, use_tpu=False)
    srv = Server(
        core.service,
        ServerConfig(http_listen_addr="127.0.0.1:0", grpc_listen_addr="127.0.0.1:0"),
    )
    srv.start()
    yield srv
    srv.stop()
    core.close()


def http_get_status(server, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{server.http_port}{path}") as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def grpc_health_check(server):
    with grpc.insecure_channel(f"127.0.0.1:{server.grpc_port}") as ch:
        stub = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        return stub(b"", timeout=10)


@pytest.fixture
def restored_readiness():
    rs = readiness_state()
    yield rs
    rs.mark_ready()
    rs.bind_health(None)


class TestServerReadiness:
    def test_ready_after_bootstrap_without_warmup(self, server):
        status, body, _ = http_get_status(server, "/_cerbos/ready")
        assert status == 200
        assert body["status"] == "ready"

    def test_liveness_stays_green_while_warming(self, server, restored_readiness):
        restored_readiness.begin_warmup(expected=2)
        status, body, _ = http_get_status(server, "/_cerbos/health")
        assert status == 200  # liveness never gates on warmup
        status, body, _ = http_get_status(server, "/_cerbos/ready")
        assert status == 503
        # snapshot may carry extra fields (e.g. policy_epoch from the rollout
        # controller) -- assert the warmup-shaped subset
        assert body["status"] == "warming"
        assert body["compiled_layouts"] == 0
        assert body["expected"] == 2

    def test_ready_flips_when_warmup_completes(self, server, restored_readiness):
        restored_readiness.begin_warmup(expected=2)
        assert http_get_status(server, "/_cerbos/ready")[0] == 503
        assert grpc_health_check(server) == b"\x08\x02"  # NOT_SERVING
        restored_readiness.layout_compiled()
        restored_readiness.layout_compiled()
        restored_readiness.mark_ready()
        status, body, _ = http_get_status(server, "/_cerbos/ready")
        assert status == 200
        assert body["status"] == "ready"
        assert body["compiled_layouts"] == 2
        assert body["expected"] == 2
        assert grpc_health_check(server) == b"\x08\x01"  # SERVING

    def test_degraded_is_still_serving(self, server, restored_readiness):
        restored_readiness.bind_health(lambda: "open")
        status, body, _ = http_get_status(server, "/_cerbos/ready")
        assert status == 200
        assert body["status"] == "degraded"
        assert grpc_health_check(server) == b"\x08\x01"  # SERVING

    def test_readiness_metrics_exported(self, server):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.http_port}/_cerbos/metrics"
        ) as resp:
            text = resp.read().decode()
        assert "cerbos_tpu_readiness_state" in text
        assert "cerbos_tpu_warmup_expected_layouts" in text

    def test_flight_header_carries_jitcache_status(self, server):
        status, _, headers = http_get_status(server, "/_cerbos/debug/flight")
        assert status == 200
        st = json.loads(headers["X-Cerbos-Jitcache"])
        assert set(st) >= {"enabled", "dir", "entries", "warm_at_enable", "persistent_loads"}

    def test_profile_endpoint_is_operator_gated(self, server):
        profiler.configure()  # disabled
        status, body, _ = http_get_status(server, "/_cerbos/debug/profile?seconds=1")
        assert status == 403
        assert "disabled" in body["error"]

    def test_profile_endpoint_captures_when_enabled(self, server, tmp_path, monkeypatch):
        profiler.configure(enabled=True, dir=str(tmp_path), max_seconds=0.05)
        monkeypatch.setattr(
            profiler, "_run_trace", lambda path, seconds: os.makedirs(path, exist_ok=True)
        )
        try:
            status, body, _ = http_get_status(server, "/_cerbos/debug/profile?seconds=9")
            assert status == 200
            assert body["seconds"] == 0.05
            assert os.path.isdir(body["path"])
            status, body, _ = http_get_status(server, "/_cerbos/debug/profile?seconds=bogus")
            assert status == 400
        finally:
            profiler.configure()
