import datetime as dt

import pytest

from cerbos_tpu.cel import CelError, parse, evaluate, check
from cerbos_tpu.cel.checker import CheckError
from cerbos_tpu.cel.interp import Activation, Message
from cerbos_tpu.cel.values import Timestamp, UInt


def ev(src, vars=None, now=None):
    now_fn = (lambda: now) if now is not None else (lambda: Timestamp.from_datetime(dt.datetime(2024, 1, 2, 3, 4, 5, tzinfo=dt.timezone.utc)))
    return evaluate(parse(src), Activation(vars or {}, now_fn=now_fn))


class TestLiteralsAndArithmetic:
    def test_ints(self):
        assert ev("1 + 2 * 3") == 7
        assert ev("(1 + 2) * 3") == 9
        assert ev("7 / 2") == 3
        assert ev("-7 / 2") == -3
        assert ev("7 % -2") == 1
        assert ev("-7 % 2") == -1
        assert ev("0x1F") == 31

    def test_int_overflow(self):
        with pytest.raises(CelError):
            ev("9223372036854775807 + 1")
        assert ev("-9223372036854775808") == -(2**63)

    def test_uint(self):
        assert ev("2u + 3u") == UInt(5)
        with pytest.raises(CelError):
            ev("2u - 3u")
        with pytest.raises(CelError):
            ev("1 + 2u")

    def test_double(self):
        assert ev("1.5 + 2.25") == 3.75
        assert ev("1.0 / 0.0") == float("inf")
        assert ev("1e3") == 1000.0

    def test_mixed_arith_is_error(self):
        with pytest.raises(CelError):
            ev("1 + 1.0")

    def test_string_concat(self):
        assert ev("'foo' + \"bar\"") == "foobar"
        assert ev("b'ab' + b'cd'") == b"abcd"
        assert ev("[1, 2] + [3]") == [1, 2, 3]

    def test_string_escapes(self):
        assert ev(r"'a\nb'") == "a\nb"
        assert ev(r"'é'") == "é"
        assert ev("r'a\\nb'") == "a\\nb"

    def test_div_by_zero(self):
        with pytest.raises(CelError):
            ev("1 / 0")
        with pytest.raises(CelError):
            ev("1 % 0")


class TestComparison:
    def test_numeric_cross_type(self):
        assert ev("1 == 1.0") is True
        assert ev("1 < 1.5") is True
        assert ev("2u == 2") is True
        assert ev("1 == '1'") is False

    def test_ordering(self):
        assert ev("'abc' < 'abd'") is True
        assert ev("b'a' < b'b'") is True
        with pytest.raises(CelError):
            ev("'a' < 1")

    def test_deep_equality(self):
        assert ev("[1, [2, 3]] == [1, [2, 3]]") is True
        assert ev("{'a': 1} == {'a': 1}") is True
        assert ev("{'a': 1} == {'a': 2}") is False

    def test_in(self):
        assert ev("2 in [1, 2, 3]") is True
        assert ev("'x' in {'x': 1}") is True
        assert ev("4 in [1, 2, 3]") is False


class TestLogic:
    def test_short_circuit_absorbs_errors(self):
        assert ev("true || (1 / 0 > 0)") is True
        assert ev("(1 / 0 > 0) || true") is True
        assert ev("false && (1 / 0 > 0)") is False
        assert ev("(1 / 0 > 0) && false") is False
        with pytest.raises(CelError):
            ev("false || (1 / 0 > 0)")
        with pytest.raises(CelError):
            ev("true && (1 / 0 > 0)")

    def test_ternary(self):
        assert ev("1 < 2 ? 'y' : 'n'") == "y"
        with pytest.raises(CelError):
            ev("1 ? 'y' : 'n'")

    def test_not(self):
        assert ev("!false") is True
        assert ev("!!true") is True


class TestStringsAndLists:
    def test_string_methods(self):
        assert ev("'hello'.contains('ell')") is True
        assert ev("'hello'.startsWith('he')") is True
        assert ev("'hello'.endsWith('lo')") is True
        assert ev("'hello'.matches('^h.*o$')") is True
        assert ev("'hello'.size()") == 5
        assert ev("size('hello')") == 5
        assert ev("'Hello'.lowerAscii()") == "hello"
        assert ev("'a,b,c'.split(',')") == ["a", "b", "c"]
        assert ev("' x '.trim()") == "x"
        assert ev("'hello'.substring(1, 3)") == "el"
        assert ev("'hello'.replace('l', 'L')") == "heLLo"
        assert ev("['a','b'].join('-')") == "a-b"
        assert ev("'hello'.indexOf('l')") == 2
        assert ev("'hello'.charAt(1)") == "e"

    def test_list_methods(self):
        assert ev("[[1],[2,3]].flatten()") == [1, 2, 3]
        assert ev("[1,2,3,4].slice(1, 3)") == [2, 3]
        assert ev("[3,1,2].sort()") == [1, 2, 3]
        assert ev("[1,1,2].distinct()") == [1, 2]
        assert ev("[1,2,3].reverse()") == [3, 2, 1]

    def test_macros(self):
        assert ev("[1,2,3].all(x, x > 0)") is True
        assert ev("[1,2,3].exists(x, x == 2)") is True
        assert ev("[1,2,3].exists_one(x, x > 2)") is True
        assert ev("[1,2,3].map(x, x * 2)") == [2, 4, 6]
        assert ev("[1,2,3].filter(x, x % 2 == 1)") == [1, 3]
        assert ev("[1,2,3].map(x, x > 1, x * 10)") == [20, 30]
        assert ev("{'a':1,'b':2}.exists(k, k == 'a')") is True

    def test_macro_error_absorption(self):
        # exists absorbs errors if a match is found
        assert ev("[0, 1].exists(x, 1 / x > 0)") is True
        with pytest.raises(CelError):
            ev("[0, 0].exists(x, 1 / x > 0)")

    def test_two_var_comprehensions(self):
        assert ev("{'a':1,'b':2}.all(k, v, v > 0)") is True
        assert ev("[10, 20].exists(i, v, i == 1 && v == 20)") is True
        assert ev("{'a':1}.transformList(k, v, k)") == ["a"]
        assert ev("{'a':1,'b':2}.transformMap(k, v, v * 10)") == {"a": 10, "b": 20}

    def test_bind(self):
        assert ev("cel.bind(x, 40, x + 2)") == 42


class TestHasMacro:
    def test_has_on_map(self):
        assert ev("has(m.a)", {"m": {"a": 1}}) is True
        assert ev("has(m.b)", {"m": {"a": 1}}) is False

    def test_missing_key_is_error(self):
        with pytest.raises(CelError):
            ev("m.b == 1", {"m": {"a": 1}})


class TestConversionsAndTime:
    def test_conversions(self):
        assert ev("int('42')") == 42
        assert ev("int(3.9)") == 3
        assert ev("double('2.5')") == 2.5
        assert ev("string(42)") == "42"
        assert ev("string(1.0)") == "1"
        assert ev("string(true)") == "true"
        assert ev("uint(7)") == UInt(7)
        assert ev("bool('true')") is True
        assert ev("type(1) == int") is True
        assert ev("type('a') == string") is True
        assert ev("type(type(1)) == type") is True

    def test_timestamp(self):
        assert ev("timestamp('2024-01-01T00:00:00Z').getFullYear()") == 2024
        assert ev("timestamp('2024-03-05T10:20:30Z').getMonth()") == 2
        assert ev("timestamp('2024-03-05T10:20:30Z').getDate()") == 5
        assert ev("timestamp('2024-03-05T10:20:30Z').getHours()") == 10
        assert ev("timestamp('2024-01-01T10:00:00Z') < timestamp('2024-01-02T10:00:00Z')") is True

    def test_duration(self):
        assert ev("duration('1h30m').getMinutes()") == 90
        assert ev("duration('90s') == duration('1m30s')") is True
        assert ev("timestamp('2024-01-01T00:00:00Z') + duration('24h') == timestamp('2024-01-02T00:00:00Z')") is True

    def test_now_is_stable(self):
        now = Timestamp.from_datetime(dt.datetime(2024, 6, 1, tzinfo=dt.timezone.utc))
        assert ev("now() == now()", now=now) is True
        assert ev("now().getFullYear()", now=now) == 2024
        assert ev("timeSince(timestamp('2024-05-31T00:00:00Z')) == duration('24h')", now=now) is True


class TestCerbosLib:
    def test_set_ops(self):
        assert ev("hasIntersection([1,2], [2,3])") is True
        assert ev("[1,2].hasIntersection([3,4])") is False
        assert ev("intersect([1,2,3], [2,3,4])") == [2, 3]
        assert ev("except([1,2,3], [2])") == [1, 3]
        assert ev("isSubset([1,2], [1,2,3])") is True
        assert ev("['a'].isSubset(['a','b'])") is True

    def test_ip_range(self):
        assert ev("'10.1.2.3'.inIPAddrRange('10.0.0.0/8')") is True
        assert ev("'192.168.1.1'.inIPAddrRange('10.0.0.0/8')") is False

    def test_paths(self):
        assert ev("basePath('/a/b/c.txt')") == "c.txt"
        assert ev("dirPath('/a/b/c.txt')") == "/a/b"
        assert ev("extPath('/a/b/c.txt')") == ".txt"
        assert ev("joinPath(['/a', 'b', 'c'])") == "/a/b/c"
        assert ev("pathHasPrefix('/a/b/c', '/a/b')") is True
        assert ev("pathHasPrefix('/a/bc', '/a/b')") is False
        assert ev("pathMatch('/a/b', '/a/*')") is True

    def test_hierarchy(self):
        assert ev("hierarchy('a.b.c').ancestorOf(hierarchy('a.b.c.d'))") is True
        assert ev("hierarchy('a.b').descendentOf(hierarchy('a'))") is True
        assert ev("hierarchy('a.b').siblingOf(hierarchy('a.c'))") is True
        assert ev("hierarchy('a.b.c').immediateChildOf(hierarchy('a.b'))") is True
        assert ev("hierarchy('a.b').overlaps(hierarchy('a.b.c'))") is True


class TestMathExt:
    def test_math(self):
        assert ev("math.greatest(1, 2, 3)") == 3
        assert ev("math.least([5, 2, 8])") == 2
        assert ev("math.ceil(1.2)") == 2.0
        assert ev("math.floor(1.8)") == 1.0
        assert ev("math.round(1.5)") == 2.0
        assert ev("math.abs(-3)") == 3
        assert ev("math.sign(-2.5)") == -1.0

    def test_encoders(self):
        assert ev("base64.encode(b'hello')") == "aGVsbG8="
        assert ev("base64.decode('aGVsbG8=')") == b"hello"


class TestRequestShape:
    def _request_vars(self):
        principal = Message({
            "id": "john", "roles": ["employee"],
            "attr": {"dept": "mkt", "clearance": 3.0},
            "policyVersion": "default", "scope": "",
        })
        resource = Message({
            "kind": "leave_request", "id": "XX1",
            "attr": {"owner": "john", "tags": ["a", "b"]},
            "policyVersion": "default", "scope": "",
        })
        request = Message({"principal": principal, "resource": resource, "auxData": Message({"jwt": {}})})
        return {"request": request, "P": principal, "R": resource, "V": {}, "variables": {}}

    def test_select_chain(self):
        v = self._request_vars()
        assert ev("request.principal.id == 'john'", v) is True
        assert ev("P.attr.dept == 'mkt'", v) is True
        assert ev("R.attr.owner == request.principal.id", v) is True
        assert ev("'employee' in P.roles", v) is True
        assert ev("P.attr.clearance >= 3.0", v) is True

    def test_missing_attr_error(self):
        v = self._request_vars()
        with pytest.raises(CelError):
            ev("R.attr.nonexistent == 'x'", v)
        assert ev("has(R.attr.nonexistent)", v) is False
        assert ev("has(R.attr.owner)", v) is True


class TestChecker:
    def test_unknown_root(self):
        with pytest.raises(CheckError):
            check(parse("unknown_var == 1"))

    def test_bad_request_field(self):
        with pytest.raises(CheckError):
            check(parse("request.bogus == 1"))
        with pytest.raises(CheckError):
            check(parse("R.attrs.x == 1"))

    def test_good_exprs(self):
        check(parse("R.attr.x == P.attr.y && 'a' in P.roles"))
        check(parse("[1,2].all(x, x > 0)"))
        check(parse("cel.bind(v, R.attr.x, v + v)"))


class TestReviewRegressions:
    """Regressions from the initial code review findings."""

    def test_relation_chains_left_assoc(self):
        assert ev("1 < 2 == true") is True
        assert ev("1 in [1] == true") is True

    def test_negative_duration_accessors(self):
        assert ev("duration('-90m').getHours()") == -1
        assert ev("duration('-90m').getMinutes()") == -90
        # total milliseconds, not the component (cel_eval/duration_funcs.yaml)
        assert ev("duration('-1500ms').getMilliseconds()") == -1500
        assert ev("duration('-1500ms').getSeconds()") == -1

    def test_nan_division(self):
        assert ev("math.isNaN(double('nan') / 0.0)") is True
        assert ev("0.0 / 0.0 != 0.0 / 0.0") is True

    def test_pre_epoch_int_conversion(self):
        assert ev("int(timestamp('1969-12-31T23:59:59.5Z'))") == -1

    def test_bad_escapes_are_parse_errors(self):
        from cerbos_tpu.cel.errors import CelParseError

        for bad in [r"'\xzz'", "0x", r"'\u12'", r"'\09'"]:
            with pytest.raises(CelParseError):
                parse(bad)

    def test_deep_nesting_is_parse_error(self):
        from cerbos_tpu.cel.errors import CelParseError

        with pytest.raises(CelParseError):
            parse("(" * 200 + "1" + ")" * 200)

    def test_map_key_type_discrimination(self):
        # Python would conflate True/1 as dict keys; CEL must not
        assert ev("{1: 'a'}[1]") == "a"
        with pytest.raises(CelError):
            ev("{1: 'a'}[true]")
        assert ev("true in {1: 'a'}") is False
        assert ev("1 in {1: 'a'}") is True


class TestReviewRegressions2:
    def test_timestamp_overflow_is_cel_error(self):
        # malformed attribute values must fail the condition, not crash
        with pytest.raises(CelError):
            ev("timestamp(999999999999999)")
        with pytest.raises(CelError):
            ev("timestamp('9999-12-31T23:59:59Z') + duration('100000h')")
        with pytest.raises(CelError):
            ev("duration(99999999999999999)")
        # absorbed by ||
        assert ev("true || timestamp(999999999999999) > now()") is True

    def test_bytes_hex_escapes_are_raw(self):
        assert ev(r'size(b"\xff")') == 1
        assert ev(r'b"\xff"') == b"\xff"
        assert ev(r'b"\377"') == b"\xff"
        assert ev(r'b"ÿ"') == b"\xc3\xbf"  # \u escapes stay code points
        assert ev(r'"\xff"') == "\xff"

    def test_negated_class_matches_separator_like_gobwas(self):
        # gobwas List/Range matchers are not separator-aware; only * and ?
        # exclude the separator.
        from cerbos_tpu.globs import matches_glob

        assert matches_glob("a[!b]c", "a:c")
        assert not matches_glob("a?c", "a:c")


def test_bytes_unicode_escape_rejected():
    from cerbos_tpu.cel.errors import CelParseError

    with pytest.raises(CelParseError):
        parse('b"\\u00e9"')
    with pytest.raises(CelParseError):
        parse('b"\\U000000e9"')
    # plain unicode characters in bytes literals are fine (UTF-8 encoded)
    assert evaluate(parse('b"é"'), Activation({})) == "é".encode()


class TestSpiffe:
    def test_spiffe_ids(self):
        assert ev("spiffeID('spiffe://example.org/workload').path()") == "/workload"
        assert ev("spiffeID('spiffe://example.org/w').trustDomain().name()") == "example.org"
        assert ev("spiffeID('spiffe://example.org/w').isMemberOf(spiffeTrustDomain('example.org'))") is True
        assert ev("spiffeID('spiffe://other.org/w').isMemberOf(spiffeTrustDomain('example.org'))") is False
        # string equality by URI, td from full URI, td.id() is a string
        assert ev("spiffeID('spiffe://a.b/c') == 'spiffe://a.b/c'") is True
        assert ev("spiffeTrustDomain('spiffe://example.org/workload').name()") == "example.org"
        assert ev("spiffeTrustDomain(spiffeID('spiffe://a.b/c')).name()") == "a.b"
        assert ev("spiffeTrustDomain('a.b').id() == 'spiffe://a.b'") is True

    def test_spiffe_matchers(self):
        assert ev("spiffeMatchAny().matchesID(spiffeID('spiffe://a.b/c'))") is True
        assert ev("spiffeMatchExact(spiffeID('spiffe://a.b/c')).matchesID('spiffe://a.b/c')") is True
        assert ev("spiffeMatchExact(spiffeID('spiffe://a.b/c')).matchesID('spiffe://a.b/d')") is False
        assert ev("spiffeMatchOneOf(['spiffe://a.b/c', 'spiffe://a.b/d']).matchesID('spiffe://a.b/d')") is True
        assert ev("spiffeMatchTrustDomain('a.b').matchesID('spiffe://a.b/zzz')") is True
        assert ev("spiffeMatchTrustDomain('a.b').matchesID('spiffe://x.y/zzz')") is False

    def test_invalid_spiffe(self):
        # malformed IDs fail closed, matching go-spiffe validation
        for bad in ["'http://nope'", "'spiffe://Example.Org/w'", "'spiffe://a.b/c/../d'",
                    "'spiffe://a.b//x'", "'spiffe://a b/c'", "'spiffe://a.b/c/'"]:
            with pytest.raises(CelError):
                ev(f"spiffeID({bad})")
        with pytest.raises(CelError):
            ev("spiffeTrustDomain('Upper.Case')")
