"""fastpred (vectorized host predicates) must be bit-exact vs the CEL
interpreter path for every value shape: matching strings, wrong types,
missing attributes, malformed IPs, IPv6, leading-zero octets.

Two layers:
  1. direct program-vs-interpreter equivalence on the compiled PredSpecs;
  2. end-to-end evaluator-vs-oracle parity through TpuEvaluator.
"""

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table
from cerbos_tpu.ruletable.check import EvalContext, build_request_messages, check_input
from cerbos_tpu.tpu import TpuEvaluator
from cerbos_tpu.tpu.condcompile import evaluate_pred_host
from cerbos_tpu.tpu import fastpred
from cerbos_tpu.tpu.packer import _ERR_SENTINEL, _MISSING_SENTINEL

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: doc
  version: "default"
  rules:
    - actions: ["read"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.name.startsWith("n1")
    - actions: ["write"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: >-
            R.attr.geography ==
            (P.attr.ip_address.inIPAddrRange("10.20.0.0/16") ? "GB" : "")
    - actions: ["tail"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.name.endsWith("z")
    - actions: ["find"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: R.attr.name.contains("mid")
    - actions: ["vsix"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: P.attr.ip_address.inIPAddrRange("2001:db8::/32")
"""

IPS = [
    "10.20.1.2",        # in 10.20.0.0/16
    "10.21.1.2",        # out
    "10.020.1.2",       # leading zero -> parse error
    "10.20.1",          # short -> error
    "10.20.1.256",      # octet range -> error
    "300.1.1.1",        # octet range -> error
    " 10.20.1.2",       # whitespace -> error
    "10.20.1.2.3",      # long -> error
    "2001:db8::1",      # v6: version mismatch for v4 net; inside v6 net
    "2001:db9::1",      # v6 outside v6 net
    "::ffff:10.20.1.2", # v4-mapped v6 literal
    "not-an-ip",
    "",
]

NAMES = ["n1-doc", "n2-doc", "xmidz", "n1", "", "midz", "zzz"]

WEIRD = [1, 1.5, True, None, ["n1"], {"a": 1}, b"n1"]


def _battery():
    """CheckInputs covering every adversarial combination."""
    inputs = []
    i = 0
    for ip in IPS:
        for name in NAMES[:3]:
            inputs.append(
                CheckInput(
                    request_id=f"r{i}",
                    principal=Principal(id=f"u{i}", roles=["user"], attr={"ip_address": ip}),
                    resource=Resource(kind="doc", id=f"d{i}", attr={"name": name, "geography": "GB"}),
                    actions=["read", "write", "tail", "find", "vsix"],
                )
            )
            i += 1
    for name in NAMES:
        for geo in ("GB", "", "FR", 7):
            inputs.append(
                CheckInput(
                    request_id=f"r{i}",
                    principal=Principal(id=f"u{i}", roles=["user"], attr={"ip_address": "10.20.3.4"}),
                    resource=Resource(kind="doc", id=f"d{i}", attr={"name": name, "geography": geo}),
                    actions=["read", "write", "tail", "find", "vsix"],
                )
            )
            i += 1
    for w in WEIRD:
        inputs.append(
            CheckInput(
                request_id=f"r{i}",
                principal=Principal(id=f"u{i}", roles=["user"], attr={"ip_address": w}),
                resource=Resource(kind="doc", id=f"d{i}", attr={"name": w} if not isinstance(w, dict) else {"name": "x"}),
                actions=["read", "write", "tail", "find", "vsix"],
            )
        )
        i += 1
    # missing attributes entirely
    inputs.append(
        CheckInput(
            request_id=f"r{i}",
            principal=Principal(id="u-miss", roles=["user"], attr={}),
            resource=Resource(kind="doc", id="d-miss", attr={}),
            actions=["read", "write", "tail", "find", "vsix"],
        )
    )
    return inputs


@pytest.fixture(scope="module")
def rt():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def test_fast_programs_compile(rt):
    ev = TpuEvaluator(rt, use_jax=False, min_device_batch=1)
    specs = ev.lowered.compiler.preds
    assert specs, "policy should produce host predicate columns"
    fastpred.configure(_MISSING_SENTINEL, _ERR_SENTINEL)
    compiled = [fastpred.compile_fast_pred(s) for s in specs]
    assert all(p is not None for p in compiled), [
        getattr(s.node, "fn", s.node) for s, p in zip(specs, compiled) if p is None
    ]


def test_program_matches_interpreter(rt):
    ev = TpuEvaluator(rt, use_jax=False, min_device_batch=1)
    pk = ev.packer
    params = EvalParams()
    inputs = _battery()
    for spec in ev.lowered.compiler.preds:
        prog = pk._fast_pred_prog(spec)
        assert prog is not None
        gathered = {
            p: [pk._path_accessor(p)(inp) for inp in inputs] for p in prog.paths
        }
        v_list, e_list = prog.eval(gathered, len(inputs))
        for i, inp in enumerate(inputs):
            request, principal, resource = build_request_messages(inp)
            ec = EvalContext(params, request, principal, resource)

            def act_factory(pparams):
                variables = ec.evaluate_variables(pparams.constants, pparams.ordered_variables)
                return ec.activation(pparams.constants, variables)

            want = evaluate_pred_host(spec, inp, act_factory)
            got = (bool(v_list[i]) and not e_list[i], bool(e_list[i]))
            assert got == want, (
                f"pred {spec.pred_id} input {i} attrs "
                f"p={inp.principal.attr} r={inp.resource.attr}: got {got} want {want}"
            )


def test_fast_iso_key():
    """_fast_iso_key must agree with the generic CEL conversion wherever it
    claims a result, and decline (None) anything the generic path rejects."""
    import random

    from cerbos_tpu.cel.errors import CelError
    from cerbos_tpu.tpu.columns import _fast_iso_key, timestamp_key
    from cerbos_tpu.cel.stdlib import _to_timestamp

    def generic_key(s):
        import datetime as dt

        ts = _to_timestamp(s)
        epoch = dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc)
        micros = (ts - epoch) // dt.timedelta(microseconds=1)
        from cerbos_tpu.tpu.columns import split_key

        return split_key((micros + (1 << 63)) & ((1 << 64) - 1))

    rng = random.Random(42)
    cases = [
        "1970-01-01T00:00:00Z", "2000-02-29T23:59:59Z", "1900-02-28T12:00:00Z",
        "9999-12-31T23:59:59Z", "0001-01-01T00:00:00Z", "2026-07-29T10:11:12Z",
    ]
    for _ in range(300):
        y, mo, d = rng.randint(1, 9999), rng.randint(1, 12), rng.randint(1, 28)
        h, mi, s = rng.randint(0, 23), rng.randint(0, 59), rng.randint(0, 59)
        cases.append(f"{y:04d}-{mo:02d}-{d:02d}T{h:02d}:{mi:02d}:{s:02d}Z")
    for s in cases:
        assert _fast_iso_key(s) == generic_key(s), s

    # invalid or out-of-shape values must decline so the generic error path runs
    bad = [
        "2026-13-01T00:00:00Z", "2026-02-30T00:00:00Z", "2026-01-01T24:00:00Z",
        "2026-01-01T00:60:00Z", "2026-01-01T00:00:60Z", "0000-01-01T00:00:00Z",
        "2026-1-01T00:00:00Z", "2026-01-01 00:00:00Z", "2026-01-01T00:00:00",
        "2026-01-01T00:00:00+00:00", "٢٠٢٦-01-01T00:00:00Z", "2026-01-01T00:00:00.5Z",
    ]
    for s in bad:
        assert _fast_iso_key(s) is None, s
    # and the full timestamp_key must keep raising on genuinely bad values
    import pytest as _pytest

    for s in ("2026-13-01T00:00:00Z", "garbage"):
        with _pytest.raises((CelError, ValueError)):
            timestamp_key(s)


def test_end_to_end_oracle_parity(rt):
    ev = TpuEvaluator(rt, use_jax=False, min_device_batch=1)
    params = EvalParams()
    inputs = _battery()
    outs = ev.check(inputs, params)
    for inp, out in zip(inputs, outs):
        oracle = check_input(rt, inp, params, None)
        assert {a: e.effect for a, e in out.actions.items()} == {
            a: e.effect for a, e in oracle.actions.items()
        }, (inp.principal.attr, inp.resource.attr)
