"""Chaos suite for the device-path fault domain (docs/ROBUSTNESS.md).

Uses the FaultInjector to inject deterministic device failures and proves
the acceptance criteria of the robustness tentpole: the breaker opens
within its failure threshold and keeps latency off the 30s timeout path; a
poison input degrades only itself; deadlines drop dead requests; a dead
drain loop fails fast; and degraded-mode decisions stay bit-exact vs the
CPU oracle.
"""

import concurrent.futures
import time
from collections import deque
from concurrent.futures import Future

import pytest

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, EvalParams, Principal, Resource
from cerbos_tpu.engine import batcher as batcher_mod
from cerbos_tpu.engine.batcher import BatchingEvaluator, DeadlineExceeded, _Pending
from cerbos_tpu.engine.faults import DeviceFault, FaultInjector, parse_fault_spec
from cerbos_tpu.engine.health import DeviceHealth
from cerbos_tpu.observability import metrics
from cerbos_tpu.policy.parser import parse_policies
from cerbos_tpu.ruletable import build_rule_table, check_input

pytestmark = pytest.mark.chaos

POLICY = """
apiVersion: api.cerbos.dev/v1
resourcePolicy:
  resource: album
  version: default
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [user]
      condition:
        match:
          expr: request.resource.attr.owner == request.principal.id || request.resource.attr.public == true
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
"""


def table():
    return build_rule_table(compile_policy_set(list(parse_policies(POLICY))))


def inp(i: int, **attr) -> CheckInput:
    return CheckInput(
        principal=Principal(id=f"u{i}", roles=["user"]),
        resource=Resource(
            kind="album",
            id=f"a{i}",
            attr={"owner": f"u{i % 7}", "public": i % 3 == 0, **attr},
        ),
        actions=["view"],
    )


def effects(outs):
    return [{a: (e.effect, e.policy) for a, e in o.actions.items()} for o in outs]


def oracle(rt, inputs, params=None):
    return [check_input(rt, i, params or EvalParams()) for i in inputs]


class OracleEvaluator:
    """Minimal streaming evaluator backed by the CPU oracle — lets the
    chaos tests exercise the batcher's fault handling without jax."""

    def __init__(self, rt):
        self.rule_table = rt
        self.schema_mgr = None
        self.stats = {"device_inputs": 0}

    def check(self, inputs, params=None):
        return oracle(self.rule_table, inputs, params)

    def submit(self, inputs, params=None):
        self.stats["device_inputs"] += len(inputs)
        return self.check(inputs, params)

    def collect(self, ticket):
        return ticket


def p99(latencies):
    ordered = sorted(latencies)
    return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]


class TestFaultSpec:
    def test_grammar(self):
        assert parse_fault_spec(
            "submit_raise:0.5, collect_delay_ms:200,wedge_after:50,poison_attr:bad,seed:42"
        ) == {
            "submit_raise": 0.5,
            "collect_delay_ms": 200,
            "wedge_after": 50,
            "poison_attr": "bad",
            "seed": 42,
        }
        assert parse_fault_spec("") == {}
        assert parse_fault_spec(None) == {}

    @pytest.mark.parametrize("bad", ["bogus:1", "submit_raise", "submit_raise:", ":0.5"])
    def test_rejects_malformed(self, bad):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)

    def test_probabilistic_injection_is_deterministic(self):
        rt = table()

        def outcomes():
            inj = FaultInjector(OracleEvaluator(rt), "submit_raise:0.5,seed:7")
            pattern = []
            for i in range(32):
                try:
                    inj.submit([inp(i)])
                    pattern.append(True)
                except DeviceFault:
                    pattern.append(False)
            return pattern

        first, second = outcomes(), outcomes()
        assert first == second
        assert True in first and False in first  # 0.5 actually fires both ways

    def test_delegates_to_wrapped_evaluator(self):
        rt = table()
        inj = FaultInjector(OracleEvaluator(rt), "")
        assert inj.rule_table is rt
        assert effects(inj.check([inp(0)])) == effects(oracle(rt, [inp(0)]))


class TestDeviceHealth:
    def test_trip_probe_reclose_cycle(self):
        clk = [0.0]
        h = DeviceHealth(
            failure_threshold=2,
            probe_backoff_base_s=1.0,
            probe_backoff_cap_s=8.0,
            probe_timeout_s=5.0,
            clock=lambda: clk[0],
        )
        assert h.allow_device()
        h.record_failure()
        assert h.state == "closed"
        h.record_failure()
        assert h.state == "open" and not h.allow_device()
        assert h.stats["trips"] == 1
        assert h.should_probe() is None  # backoff (1s) not elapsed
        clk[0] = 1.1
        tok = h.should_probe()
        assert tok is not None and h.state == "half_open"
        assert h.should_probe() is None  # one probe at a time
        h.probe_failed(tok)
        assert h.state == "open"
        clk[0] = 2.0
        assert h.should_probe() is None  # second backoff doubled to 2s
        clk[0] = 3.2
        tok2 = h.should_probe()
        assert tok2 is not None
        h.probe_succeeded(tok2)
        assert h.state == "closed" and h.allow_device()

    def test_success_resets_consecutive_failures(self):
        h = DeviceHealth(failure_threshold=3)
        h.record_failure()
        h.record_failure()
        h.record_success()
        h.record_failure()
        h.record_failure()
        assert h.state == "closed"

    def test_timeout_rate_trip(self):
        clk = [0.0]
        h = DeviceHealth(
            timeout_rate_threshold=0.5, timeout_min_samples=4, clock=lambda: clk[0]
        )
        h.record_success()
        h.record_success()
        h.record_timeout()
        assert h.state == "closed"  # 1/3 below min samples + rate
        h.record_timeout()
        assert h.state == "open"  # 2/4 hits the 50% rate
        assert h.stats["trips"] == 1

    def test_wedged_probe_expires_and_reopens(self):
        clk = [0.0]
        h = DeviceHealth(
            failure_threshold=1,
            probe_backoff_base_s=1.0,
            probe_timeout_s=2.0,
            clock=lambda: clk[0],
        )
        h.record_failure()
        clk[0] = 1.5
        tok = h.should_probe()
        assert tok is not None and h.state == "half_open"
        clk[0] = 4.0  # probe never reported back: expire it
        assert h.state == "open"
        h.probe_succeeded(tok)  # the wedged probe's late result is stale
        assert h.state == "open"

    def test_disabled_never_trips(self):
        h = DeviceHealth(failure_threshold=1, enabled=False)
        for _ in range(10):
            h.record_failure()
            h.record_timeout()
        assert h.allow_device() and h.should_probe() is None


class TestBreakerServing:
    def test_breaker_opens_and_skips_device_wait(self):
        """Acceptance: at 100% submit_raise the breaker opens within the
        failure threshold and faulted p99 stays < 2x the healthy p99 (no
        request rides out the request timeout once open)."""
        rt = table()
        healthy = BatchingEvaluator(
            OracleEvaluator(rt), max_wait_ms=0.0, request_timeout_s=30.0
        )
        lat_healthy = []
        try:
            for i in range(40):
                t0 = time.perf_counter()
                healthy.check([inp(i)])
                lat_healthy.append(time.perf_counter() - t0)
        finally:
            healthy.close()

        health = DeviceHealth(failure_threshold=3, probe_backoff_base_s=60.0)
        inj = FaultInjector(OracleEvaluator(rt), "submit_raise:1.0")
        batcher = BatchingEvaluator(
            inj, max_wait_ms=0.0, request_timeout_s=30.0, health=health
        )
        lat_faulted = []
        results = []
        try:
            for i in range(40):
                t0 = time.perf_counter()
                results.append(batcher.check([inp(i)])[0])
                lat_faulted.append(time.perf_counter() - t0)
        finally:
            batcher.close()

        assert health.state == "open"
        assert health.stats["trips"] == 1
        # breaker opened within the threshold: only the first few requests
        # ever reached the (raising) device
        assert batcher.stats["batch_errors"] <= health.failure_threshold
        fallbacks = metrics().counter_vec("cerbos_tpu_batcher_oracle_fallbacks_total")
        assert fallbacks.get("breaker_open") >= 40 - health.failure_threshold
        # every decision still correct
        assert effects(results) == effects(oracle(rt, [inp(i) for i in range(40)]))
        # latency acceptance (floor guards timer noise on tiny absolute values)
        assert p99(lat_faulted) < max(2 * p99(lat_healthy), 0.25), (
            p99(lat_faulted),
            p99(lat_healthy),
        )

    def test_breaker_recloses_via_probe(self):
        rt = table()
        health = DeviceHealth(
            failure_threshold=2, probe_backoff_base_s=0.02, probe_backoff_cap_s=0.1
        )
        inj = FaultInjector(OracleEvaluator(rt), "submit_raise:1.0")
        batcher = BatchingEvaluator(
            inj, max_wait_ms=0.0, request_timeout_s=5.0, health=health
        )
        try:
            for i in range(4):
                batcher.check([inp(i)])
            assert health.state == "open"
            inj.spec.pop("submit_raise")  # the device heals
            deadline = time.monotonic() + 10.0
            while health.state != "closed" and time.monotonic() < deadline:
                batcher.check([inp(1)])  # oracle-served; donates probe inputs
                time.sleep(0.01)
            assert health.state == "closed"
            assert health.stats["probes"] >= 1
            # live traffic is back on the device path
            before = batcher.stats["batches"]
            out = batcher.check([inp(2)])
            assert batcher.stats["batches"] == before + 1
            assert effects(out) == effects(oracle(rt, [inp(2)]))
        finally:
            batcher.close()


class TestPoisonQuarantine:
    def test_poison_degrades_only_itself(self):
        """Acceptance: a poison input fails its batch, but co-batched
        requests all get correct answers (never an error), and the poison is
        bisected out and quarantined."""
        rt = table()
        inj = FaultInjector(OracleEvaluator(rt), "poison_attr:poison")
        health = DeviceHealth(failure_threshold=100)  # keep the breaker out of this test
        batcher = BatchingEvaluator(
            inj,
            max_wait_ms=200.0,
            min_batch_to_wait=9,
            request_timeout_s=10.0,
            health=health,
        )
        poison = inp(99, poison=True)
        goods = [inp(i) for i in range(8)]
        try:
            # a concurrent burst so poison and innocents co-batch
            with concurrent.futures.ThreadPoolExecutor(max_workers=9) as pool:
                good_futs = [pool.submit(batcher.check, [g]) for g in goods]
                poison_fut = pool.submit(batcher.check, [poison])
                good_results = [f.result(timeout=15)[0] for f in good_futs]
                poison_result = poison_fut.result(timeout=15)
            # nobody errored, everybody is bit-exact vs the oracle
            assert effects(good_results) == effects(oracle(rt, goods))
            assert effects(poison_result) == effects(oracle(rt, [poison]))
            assert batcher.stats["batch_errors"] >= 1
            # the off-path bisect identifies and quarantines exactly the poison
            deadline = time.monotonic() + 10.0
            while batcher.stats["quarantined"] < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert batcher.stats["quarantined"] == 1
            assert metrics().counter("cerbos_tpu_batcher_quarantined_total").value >= 1
            # re-requesting the poison bypasses batching entirely...
            before = batcher.stats["batches"]
            out = batcher.check([poison])
            assert batcher.stats["batches"] == before
            assert effects(out) == effects(oracle(rt, [poison]))
            fallbacks = metrics().counter_vec("cerbos_tpu_batcher_oracle_fallbacks_total")
            assert fallbacks.get("quarantine") >= 1
            # ...while innocents still ride the device path
            out2 = batcher.check([goods[0]])
            assert batcher.stats["batches"] == before + 1
            assert effects(out2) == effects(oracle(rt, [goods[0]]))
        finally:
            batcher.close()

    def test_whole_device_failure_quarantines_nothing(self):
        """When every sub-batch fails (device down, not poison), the bisect
        must not quarantine innocent inputs."""
        rt = table()
        inj = FaultInjector(OracleEvaluator(rt), "submit_raise:1.0,check_raise:1.0")
        health = DeviceHealth(failure_threshold=100)
        batcher = BatchingEvaluator(
            inj,
            max_wait_ms=200.0,
            min_batch_to_wait=4,
            request_timeout_s=10.0,
            health=health,
        )
        try:
            with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
                futs = [pool.submit(batcher.check, [inp(i)]) for i in range(4)]
                results = [f.result(timeout=15)[0] for f in futs]
            assert effects(results) == effects(oracle(rt, [inp(i) for i in range(4)]))
            # give the bisect thread a beat, then confirm it stayed silent
            deadline = time.monotonic() + 2.0
            while batcher._bisect_busy and time.monotonic() < deadline:
                time.sleep(0.02)
            assert batcher.stats["quarantined"] == 0
        finally:
            batcher.close()

    def test_quarantine_set_is_bounded(self):
        rt = table()
        batcher = BatchingEvaluator(OracleEvaluator(rt), quarantine_max=4)
        try:
            for i in range(10):
                batcher._quarantine_add(inp(i))
            assert len(batcher._quarantine) == 4
            assert batcher.stats["quarantined"] == 10
            # oldest evicted, newest kept
            assert not batcher._has_quarantined([inp(0)])
            assert batcher._has_quarantined([inp(9)])
        finally:
            batcher.close()


class TestDeadlines:
    def test_already_expired_request_is_dropped(self):
        rt = table()
        batcher = BatchingEvaluator(OracleEvaluator(rt))
        try:
            with pytest.raises(DeadlineExceeded):
                batcher.check([inp(0)], deadline=time.monotonic() - 0.01)
            assert batcher.stats["deadline_drops"] == 1
            assert metrics().counter("cerbos_tpu_batcher_deadline_drops_total").value >= 1
        finally:
            batcher.close()

    def test_expired_while_queued_dropped_at_drain(self):
        """White-box: an already-expired _Pending in the queue is settled
        with DeadlineExceeded at drain time, not submitted to the device."""
        rt = table()
        ev = OracleEvaluator(rt)
        batcher = BatchingEvaluator(ev, max_wait_ms=0.0)
        try:
            fut: Future = Future()
            stale = _Pending([inp(0)], None, fut, deadline=time.monotonic() - 1.0)
            with batcher._wakeup:
                batcher._queue.append(stale)
                batcher._wakeup.notify()
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=5)
            assert batcher.stats["deadline_drops"] == 1
            assert ev.stats["device_inputs"] == 0  # no device work spent on it
        finally:
            batcher.close()

    def test_deadline_clamps_wait_on_wedged_device(self):
        """A request with a short deadline against a wedged device raises
        DEADLINE_EXCEEDED at its own deadline, not at the 30s timeout."""
        rt = table()

        class WedgedEvaluator(OracleEvaluator):
            def submit(self, inputs, params=None):
                time.sleep(1.0)
                return super().submit(inputs, params)

        batcher = BatchingEvaluator(
            WedgedEvaluator(rt), max_wait_ms=0.0, request_timeout_s=30.0
        )
        try:
            t0 = time.perf_counter()
            with pytest.raises(DeadlineExceeded):
                batcher.check([inp(0)], deadline=time.monotonic() + 0.1)
            assert time.perf_counter() - t0 < 1.0
        finally:
            batcher.close()

    def test_grpc_deadline_maps_to_deadline_exceeded(self):
        """An expired client deadline surfaces as gRPC DEADLINE_EXCEEDED."""
        import grpc

        from cerbos_tpu.engine.engine import Engine
        from cerbos_tpu.server.server import _grpc_rpcs
        from cerbos_tpu.server.service import CerbosService

        rt = table()
        batcher = BatchingEvaluator(OracleEvaluator(rt))
        engine = Engine(rt, tpu_evaluator=batcher, tpu_batch_threshold=1)
        svc = CerbosService(engine)
        handler = _grpc_rpcs(svc)["CheckResources"].unary_unary

        from cerbos_tpu.api.cerbos.request.v1 import request_pb2

        req = request_pb2.CheckResourcesRequest(request_id="d-1")
        p = req.principal
        p.id = "u1"
        p.roles.append("user")
        entry = req.resources.add()
        entry.actions.append("view")
        entry.resource.kind = "album"
        entry.resource.id = "a1"

        class Ctx:
            def __init__(self, remaining):
                self.code = None
                self._remaining = remaining

            def time_remaining(self):
                return self._remaining

            def abort(self, code, details):
                self.code = code
                raise RuntimeError(details)

        try:
            ctx = Ctx(remaining=-0.5)  # client deadline already expired
            with pytest.raises(RuntimeError):
                handler(req, ctx)
            assert ctx.code == grpc.StatusCode.DEADLINE_EXCEEDED
            ctx_ok = Ctx(remaining=30.0)
            resp = handler(req, ctx_ok)
            assert ctx_ok.code is None and resp.results
        finally:
            batcher.close()


class TestWatchdogAndShutdown:
    def test_drain_loop_death_fails_fast(self):
        """If the drain loop dies (BaseException out of submit), in-drain
        waiters settle immediately and later requests skip the dead thread —
        nothing hangs until the request timeout."""
        rt = table()

        class _Die(BaseException):
            pass

        class KillerEvaluator(OracleEvaluator):
            def submit(self, inputs, params=None):
                raise _Die("drain loop killed")

        batcher = BatchingEvaluator(
            KillerEvaluator(rt), max_wait_ms=0.0, request_timeout_s=30.0
        )
        try:
            t0 = time.perf_counter()
            out = batcher.check([inp(0)])
            assert time.perf_counter() - t0 < 5.0
            assert effects(out) == effects(oracle(rt, [inp(0)]))
            batcher._thread.join(timeout=5)
            assert not batcher._thread.is_alive()
            assert batcher._dead is not None
            # new requests detect the dead thread and go straight to the oracle
            out2 = batcher.check([inp(1)])
            assert effects(out2) == effects(oracle(rt, [inp(1)]))
            fallbacks = metrics().counter_vec("cerbos_tpu_batcher_oracle_fallbacks_total")
            assert fallbacks.get("batcher_dead") >= 2
        finally:
            batcher.close()

    def test_close_settles_queued_requests(self):
        """Satellite bug fix: close() under load must not strand queued
        waiters for the full request timeout."""
        rt = table()

        class SlowEvaluator(OracleEvaluator):
            def check(self, inputs, params=None):
                time.sleep(0.2)
                return super().check(inputs, params)

            submit = None  # force the sync ready-ticket path (blocks the drain loop)

        batcher = BatchingEvaluator(
            SlowEvaluator(rt), max_wait_ms=0.0, request_timeout_s=30.0
        )
        inputs = [inp(i) for i in range(12)]
        with concurrent.futures.ThreadPoolExecutor(max_workers=12) as pool:
            futs = [pool.submit(batcher.check, [inputs[0]])]
            time.sleep(0.05)  # drain loop is now sleeping inside check()
            futs += [pool.submit(batcher.check, [i]) for i in inputs[1:]]
            time.sleep(0.05)  # stragglers are queued behind the busy drain
            t0 = time.perf_counter()
            batcher.close()
            results = [f.result(timeout=10)[0] for f in futs]
            elapsed = time.perf_counter() - t0
        assert elapsed < 10.0, "queued waiters must settle at close, not at timeout"
        assert effects(results) == effects(oracle(rt, inputs))
        fallbacks = metrics().counter_vec("cerbos_tpu_batcher_oracle_fallbacks_total")
        assert fallbacks.get("shutdown") >= 1

    def test_queue_is_a_deque(self):
        """Satellite perf nit: O(1) popleft instead of list.pop(0) — the
        priority lanes keep one deque per lane."""
        rt = table()
        batcher = BatchingEvaluator(OracleEvaluator(rt))
        try:
            assert all(isinstance(lane.q, deque) for lane in batcher._queue._order)
            assert batcher._queue.depths() == {}
        finally:
            batcher.close()

    def test_oracle_import_is_hoisted(self):
        """Satellite: check_input is a module-level import, not re-imported
        on every timeout fallback."""
        assert hasattr(batcher_mod, "check_input")


class TestBootstrapWiring:
    def test_env_fault_spec_wires_injector_and_breaker(self, tmp_path, monkeypatch):
        """CERBOS_TPU_FAULTS wraps the device evaluator in a FaultInjector
        and the configured breaker trips under it — full bootstrap path."""
        from cerbos_tpu.bootstrap import initialize
        from cerbos_tpu.config import Config

        (tmp_path / "album.yaml").write_text(POLICY)
        monkeypatch.setenv("CERBOS_TPU_FAULTS", "submit_raise:1.0")
        config = Config.load(overrides=[f"storage.disk.directory={tmp_path}"])
        core = initialize(config)
        try:
            batcher = core.engine.tpu_evaluator
            assert isinstance(batcher, BatchingEvaluator)
            assert isinstance(batcher.evaluator, FaultInjector)
            assert batcher.health is not None and batcher.health.enabled
            i = inp(0)
            for _ in range(batcher.health.failure_threshold + 2):
                out = batcher.check([i])
                assert effects(out) == effects(oracle(batcher.evaluator.rule_table, [i]))
            assert batcher.health.state == "open"
        finally:
            core.close()


class TestDegradedModeParity:
    def test_degraded_mode_parity(self):
        """Acceptance: every degraded-mode decision (CPU-oracle fallback) is
        bit-exact vs the device path on the same inputs."""
        from cerbos_tpu.tpu import TpuEvaluator
        from cerbos_tpu.util import bench_corpus

        rt = build_rule_table(
            compile_policy_set(list(parse_policies(bench_corpus.corpus_yaml(8))))
        )
        ev = TpuEvaluator(rt, use_jax=True, min_device_batch=4)
        batcher = BatchingEvaluator(ev, max_wait_ms=0.0)
        inputs = bench_corpus.requests(256, 8)
        params = EvalParams()
        try:
            device = ev.check(list(inputs), params)
            degraded = batcher._serve_oracle(inputs, params, "parity_test")
        finally:
            batcher.close()
        for i, (g, w) in enumerate(zip(device, degraded)):
            assert {a: (e.effect, e.policy, e.scope) for a, e in g.actions.items()} == {
                a: (e.effect, e.policy, e.scope) for a, e in w.actions.items()
            }, f"effect mismatch for input {i}: {inputs[i]}"
            assert g.effective_derived_roles == w.effective_derived_roles, i
            assert g.effective_policies == w.effective_policies, i
            assert sorted((o.src, o.action, repr(o.val)) for o in g.outputs) == sorted(
                (o.src, o.action, repr(o.val)) for o in w.outputs
            ), i

    def test_batch_error_fallback_is_bit_exact(self):
        """The batch_error recovery path (the one production hits when a
        batch dies) returns the same decisions the healthy path would."""
        rt = table()
        inj = FaultInjector(OracleEvaluator(rt), "submit_raise:1.0")
        health = DeviceHealth(failure_threshold=100)
        batcher = BatchingEvaluator(inj, max_wait_ms=0.0, health=health)
        inputs = [inp(i) for i in range(16)]
        try:
            got = [batcher.check([i])[0] for i in inputs]
        finally:
            batcher.close()
        assert effects(got) == effects(oracle(rt, inputs))
        fallbacks = metrics().counter_vec("cerbos_tpu_batcher_oracle_fallbacks_total")
        assert fallbacks.get("batch_error") >= 16
