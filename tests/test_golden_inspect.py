"""Reference inspect corpus: policy inspection results.

Mirrors internal/inspect/inspect_test.go (Policies mode): each case's input
policies are inspected together, with import resolution falling back to a
policy loader over the same inputs, and the per-policy results compare
against policiesExpectation (attributes, constants, variables with
local/imported/exported/undefined kinds and used flags, derived roles,
actions).
"""

import os

import pytest
import yaml

from cerbos_tpu.inspect import PolicyInspector, _policy_key
from cerbos_tpu.policy.parser import parse_policy

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "inspect")

CASES = sorted(f for f in os.listdir(CORPUS) if f.endswith(".yaml"))


def _norm(v):
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in sorted(v.items()) if not _is_default(x)}
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return v


def _is_default(x):
    return x in ("", None, [], {}, False)


@pytest.mark.parametrize("case", CASES)
def test_inspect_policies(case):
    with open(os.path.join(CORPUS, case), encoding="utf-8") as f:
        tc = yaml.safe_load(f)

    policies = [parse_policy(doc) for doc in tc.get("inputs", [])]
    by_key = {_policy_key(p): p for p in policies}

    requested_missing: list[str] = []

    def load_policy(key):
        pol = by_key.get(key)
        if pol is None:
            requested_missing.append(key)
        return pol

    ins = PolicyInspector()
    for p in policies:
        ins.inspect(p)
    have = ins.results(load_policy=load_policy)

    want = (tc.get("policiesExpectation") or {}).get("policies") or {}
    missing = (tc.get("policiesExpectation") or {}).get("missingPolicies") or []
    assert sorted(want.keys()) == sorted(have.keys()), case
    for key in want:
        assert _norm(want[key]) == _norm(have[key]), f"{case}: {key}"
    assert sorted(missing) == sorted(set(requested_missing)), case
