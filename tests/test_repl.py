"""Scripted REPL session (VERDICT r3 item 9).

Drives cerbos_tpu.repl.Repl the way cmd/cerbos/repl's own tests drive its
directive handler: a sequence of lines in, assertions over the printed
output — covering expression eval with ``_``, :let (plain and special
JSON), :vars, :load of a policy dir, :rules, :exec with concrete results,
:exec producing a RESIDUAL for missing attributes, and :reset.
"""


import pytest

from cerbos_tpu.repl import Repl

POLICY = """
apiVersion: api.cerbos.dev/v1
variables:
  is_owner: R.attr.owner == P.id
resourcePolicy:
  resource: leave_request
  version: default
  importDerivedRoles: [common_roles]
  rules:
    - actions: ["view"]
      effect: EFFECT_ALLOW
      roles: [employee]
      name: view-own
      condition:
        match:
          expr: V.is_owner
    - actions: ["approve"]
      effect: EFFECT_ALLOW
      derivedRoles: [direct_manager]
      name: approve
      condition:
        match:
          expr: R.attr.status == "PENDING_APPROVAL"
    - actions: ["*"]
      effect: EFFECT_ALLOW
      roles: [admin]
      name: admin-all
"""

DERIVED = """
apiVersion: api.cerbos.dev/v1
derivedRoles:
  name: common_roles
  definitions:
    - name: direct_manager
      parentRoles: [manager]
      condition:
        match:
          expr: R.attr.managerId == P.id
"""


@pytest.fixture()
def policy_dir(tmp_path):
    (tmp_path / "leave_request.yaml").write_text(POLICY)
    (tmp_path / "derived.yaml").write_text(DERIVED)
    return str(tmp_path)


class Session:
    def __init__(self):
        self.lines: list[str] = []
        self.repl = Repl(out=self.lines.append)

    def run(self, *inputs: str) -> str:
        self.lines.clear()
        for line in inputs:
            assert self.repl.handle(line) is True
        return "\n".join(self.lines)


def test_expressions_and_underscore():
    s = Session()
    assert s.run("1 + 1") == "2"
    assert s.run("_ + 5") == "7"
    assert s.run('"test".charAt(1)') == '"e"'


def test_let_plain_and_special():
    s = Session()
    assert "x = 12" in s.run(":let x = 12")
    assert "y = 6" in s.run(":let y = 1 + 5")
    assert s.run("x + y") == "18"
    out = s.run(':let P = {"id":"john","roles":["employee"]}')
    assert "P set" in out
    assert s.run("P.id") == '"john"'
    out = s.run(":vars")
    assert '"john"' in out and '"x": 12' in out


def test_let_errors():
    s = Session()
    assert "usage" in s.run(":let x")
    assert "takes JSON" in s.run(":let P = not-json")
    assert "error:" in s.run("1 +")


def test_load_rules_exec(policy_dir):
    s = Session()
    out = s.run(f":load {policy_dir}")
    assert "loaded" in out and "rules" in out
    out = s.run(":rules")
    assert "resource.leave_request.vdefault#view-own" in out
    assert "derived:direct_manager" in out
    assert 'R.attr.status == "PENDING_APPROVAL"' in out

    # concrete true: owner matches
    s.run(':let P = {"id":"john","roles":["employee"]}')
    s.run(':let R = {"kind":"leave_request","attr":{"owner":"john","status":"OPEN"}}')
    rules_out = s.run(":rules")
    idx = next(
        i for i, line in enumerate(rules_out.splitlines())
        if "#view-own" in line
    )
    rule_no = rules_out.splitlines()[idx].split()[0]  # "#N"
    out = s.run(f":exec {rule_no}")
    assert "result: true" in out

    # concrete false: different owner
    s.run(':let R = {"kind":"leave_request","attr":{"owner":"sally","status":"OPEN"}}')
    out = s.run(f":exec {rule_no}")
    assert "result: false" in out


def test_exec_residual_for_missing_attr(policy_dir):
    s = Session()
    s.run(f":load {policy_dir}")
    s.run(':let P = {"id":"john","roles":["employee"]}')
    # resource carries NO attrs: the view-own condition over R.attr.owner
    # cannot be decided concretely -> residual referencing the attribute
    s.run(':let R = {"kind":"leave_request","attr":{}}')
    rules_out = s.run(":rules")
    idx = next(i for i, line in enumerate(rules_out.splitlines()) if "#view-own" in line)
    rule_no = rules_out.splitlines()[idx].split()[0]
    out = s.run(f":exec {rule_no}")
    assert "residual:" in out
    assert "owner" in out


def test_exec_unconditional_and_bad_refs(policy_dir):
    s = Session()
    s.run(f":load {policy_dir}")
    rules_out = s.run(":rules")
    idx = next(i for i, line in enumerate(rules_out.splitlines()) if "#admin-all" in line)
    rule_no = rules_out.splitlines()[idx].split()[0]
    out = s.run(f":exec {rule_no}")
    assert "unconditional" in out
    assert "usage" in s.run(":exec 3")
    assert "no rule" in s.run(":exec #999")


def test_reset_and_help():
    s = Session()
    s.run(":let x = 1")
    out = s.run(":reset")
    assert "cleared" in out
    assert "error:" in s.run("x")  # x is gone
    assert ":load" in s.run(":help")


def test_load_missing_path():
    s = Session()
    out = s.run(":load /nonexistent/path.yaml")
    assert "error" in out.lower()
