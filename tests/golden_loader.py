"""Loader + comparison helpers for the ported reference golden corpora.

Behavioral reference: internal/engine/engine_test.go:46-255 (TestCheck /
TestCheckWithLenientScopeSearch / TestSchemaValidation) and
internal/test/test.go (LoadTestCases). The fixtures under tests/golden/
are the reference's own testdata, ported as data per SURVEY §4 tier 1.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional

import yaml

from cerbos_tpu.compile import compile_policy_set
from cerbos_tpu.engine import CheckInput, Engine, EvalParams, Principal, Resource
from cerbos_tpu.engine.types import AuxData
from cerbos_tpu.schema import SchemaManager
from cerbos_tpu.storage import DiskStore

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
STORE_DIR = os.path.join(GOLDEN_DIR, "store")

# mkEngine sets these (engine_test.go:375-377)
GOLDEN_GLOBALS = {"environment": "test"}


def load_cases(subdir: str) -> list[tuple[str, dict]]:
    """Mirror of test.LoadTestCases: every .yaml directly in the dir, sorted."""
    d = os.path.join(GOLDEN_DIR, subdir)
    out = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".yaml"):
            continue
        with open(os.path.join(d, name)) as f:
            out.append((f"{subdir}/{name}", yaml.safe_load(f)))
    return out


@functools.lru_cache(maxsize=None)
def golden_policies():
    store = DiskStore(STORE_DIR)
    return store, compile_policy_set(store.get_all())


def golden_engine(
    lenient: bool = False,
    schema_enforcement: str = "none",
    **engine_kwargs,
) -> Engine:
    store, compiled = golden_policies()
    params = EvalParams(globals=dict(GOLDEN_GLOBALS), lenient_scope_search=lenient)
    schema_mgr = None
    if schema_enforcement != "none":
        schema_mgr = SchemaManager(store, enforcement=schema_enforcement)
    return Engine.from_policies(
        compiled, schema_mgr=schema_mgr, eval_params=params, **engine_kwargs
    )


def parse_input(raw: dict) -> CheckInput:
    p = raw["principal"]
    r = raw["resource"]
    aux = None
    if raw.get("auxData"):
        aux = AuxData(jwt=raw["auxData"].get("jwt", {}))
    return CheckInput(
        principal=Principal(
            id=p["id"],
            roles=list(p.get("roles", [])),
            attr=p.get("attr", {}) or {},
            policy_version=p.get("policyVersion", ""),
            scope=p.get("scope", ""),
        ),
        resource=Resource(
            kind=r["kind"],
            id=r.get("id", ""),
            attr=r.get("attr", {}) or {},
            policy_version=r.get("policyVersion", ""),
            scope=r.get("scope", ""),
        ),
        actions=list(raw.get("actions", [])),
        request_id=raw.get("requestId", ""),
        aux_data=aux,
    )


def _norm_val(v: Any) -> Any:
    """Expected values are parsed from YAML/JSON; ours are structpb-Value-like
    (numbers become doubles). Normalize both sides."""
    if isinstance(v, bool) or v is None or isinstance(v, str):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, list):
        return [_norm_val(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _norm_val(x) for k, x in v.items()}
    return v


def diff_output(want: dict, have) -> list[str]:
    """Compare a wantOutputs entry against a CheckOutput; return mismatch list.

    Mirrors the protocmp.Diff options in engine_test.go:85-96: outputs sorted
    by src, effective_derived_roles order-insensitive, empty==absent.
    """
    errs: list[str] = []
    if want.get("requestId", "") != have.request_id:
        errs.append(f"requestId: want {want.get('requestId')!r} have {have.request_id!r}")
    if want.get("resourceId", "") != have.resource_id:
        errs.append(f"resourceId: want {want.get('resourceId')!r} have {have.resource_id!r}")

    want_actions = want.get("actions", {})
    have_actions = have.actions
    if set(want_actions) != set(have_actions):
        errs.append(f"actions keys: want {sorted(want_actions)} have {sorted(have_actions)}")
    for action, wa in want_actions.items():
        ha = have_actions.get(action)
        if ha is None:
            continue
        if wa.get("effect") != ha.effect:
            errs.append(f"actions[{action}].effect: want {wa.get('effect')} have {ha.effect}")
        if wa.get("policy", "") != ha.policy:
            errs.append(f"actions[{action}].policy: want {wa.get('policy')!r} have {ha.policy!r}")
        if wa.get("scope", "") != ha.scope:
            errs.append(f"actions[{action}].scope: want {wa.get('scope')!r} have {ha.scope!r}")

    want_edr = sorted(want.get("effectiveDerivedRoles", want.get("effective_derived_roles", [])))
    have_edr = sorted(have.effective_derived_roles)
    if want_edr != have_edr:
        errs.append(f"effectiveDerivedRoles: want {want_edr} have {have_edr}")

    want_outputs = sorted(want.get("outputs", []), key=lambda o: o.get("src", ""))
    have_outputs = sorted(have.outputs, key=lambda o: o.src)
    if len(want_outputs) != len(have_outputs):
        errs.append(
            f"outputs count: want {len(want_outputs)} have {len(have_outputs)}"
            f" (want srcs {[o.get('src') for o in want_outputs]},"
            f" have srcs {[o.src for o in have_outputs]})"
        )
    else:
        for wo, ho in zip(want_outputs, have_outputs):
            if wo.get("src", "") != ho.src:
                errs.append(f"output src: want {wo.get('src')!r} have {ho.src!r}")
            if wo.get("action", "") != ho.action:
                errs.append(f"output[{ho.src}].action: want {wo.get('action')!r} have {ho.action!r}")
            if _norm_val(wo.get("val")) != _norm_val(ho.val):
                errs.append(f"output[{ho.src}].val: want {wo.get('val')!r} have {ho.val!r}")
            # error is a free-text message; require presence parity only
            if bool(wo.get("error")) != bool(ho.error):
                errs.append(f"output[{ho.src}].error: want {wo.get('error')!r} have {ho.error!r}")

    def _ve_key(v):
        return (v[0], v[1])

    want_ve = sorted(
        ((v.get("source", ""), v.get("path", ""), v.get("message", "")) for v in want.get("validationErrors", [])),
        key=_ve_key,
    )
    have_ve = sorted(((v.source, v.path, v.message) for v in have.validation_errors), key=_ve_key)
    if [(s, p) for s, p, _ in want_ve] != [(s, p) for s, p, _ in have_ve]:
        errs.append(f"validationErrors: want {want_ve} have {have_ve}")
    return errs


def run_case(engine: Engine, case: dict, params: Optional[EvalParams] = None) -> list[str]:
    inputs = [parse_input(raw) for raw in case.get("inputs", [])]
    outputs = engine.check(inputs, params=params)
    errs: list[str] = []
    want_outputs = case.get("wantOutputs", [])
    if len(want_outputs) != len(outputs):
        return [f"output count: want {len(want_outputs)} have {len(outputs)}"]
    for i, (want, have) in enumerate(zip(want_outputs, outputs)):
        for e in diff_output(want, have):
            errs.append(f"[{i}] {e}")
    return errs
